# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Health-propagation tests through the poll loop.

The reference leaves health_checker.go untested because it needs NVML
(SURVEY.md section 4); the chip-backend seam makes the full path
unit-testable here: state file -> poller -> manager -> ListAndWatch.
"""

import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.chip import PyChipBackend
from container_engine_accelerators_tpu.chip.backend import ChipBackendError
from container_engine_accelerators_tpu.plugin import api
from container_engine_accelerators_tpu.plugin.config import TpuConfig
from container_engine_accelerators_tpu.plugin.health import TpuHealthChecker
from container_engine_accelerators_tpu.plugin.manager import TpuManager


@pytest.fixture(autouse=True)
def clean_tracer():
    obs.TRACER.reset()
    yield
    obs.TRACER.reset()


def health_events():
    return [e for e in obs.TRACER.snapshot()["events"]
            if e["name"] == "health.transition"]


@pytest.fixture
def node4(fake_node):
    for i in range(4):
        fake_node.add_chip(i)
    fake_node.set_topology("2x2")
    return fake_node


def make(node, **kwargs):
    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=node.dev_dir, state_dir=node.state_dir,
                     backend=backend, **kwargs)
    mgr.start()
    return mgr, backend, TpuHealthChecker(mgr, backend)


def test_ecc_error_marks_device_unhealthy(node4):
    mgr, _, hc = make(node4)
    node4.set_state(1, "health", "uncorrectable_ecc")
    hc.poll_once()
    devices = mgr.list_devices()
    assert devices["accel1"] == api.UNHEALTHY
    assert devices["accel0"] == api.HEALTHY


def test_recovery_marks_healthy_again(node4):
    mgr, _, hc = make(node4)
    node4.set_state(1, "health", "wedged")
    hc.poll_once()
    assert mgr.list_devices()["accel1"] == api.UNHEALTHY
    node4.set_state(1, "health", "ok")
    hc.poll_once()
    assert mgr.list_devices()["accel1"] == api.HEALTHY


def test_unknown_state_does_not_degrade(node4):
    mgr, _, hc = make(node4)
    node4.set_state(2, "health", "some-future-token")
    hc.poll_once()
    assert mgr.list_devices()["accel2"] == api.HEALTHY


def test_backend_failure_marks_all_unhealthy(node4):
    mgr, backend, hc = make(node4)

    def boom(chip):
        raise ChipBackendError("backend gone")

    backend.chip_health = boom
    hc.poll_once()
    assert set(mgr.list_devices().values()) == {api.UNHEALTHY}


def test_bad_chip_marks_owning_subslice(node4):
    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=node4.dev_dir, state_dir=node4.state_dir,
                     tpu_config=TpuConfig(tpu_partition_size="1x2"),
                     backend=backend)
    mgr.start()
    hc = TpuHealthChecker(mgr, backend)
    node4.set_state(3, "health", "ici_link_down")
    hc.poll_once()
    devices = mgr.list_devices()
    # Chip 3 lives in the second 1x2 subslice of the 2x2 torus.
    bad = [d for d, h in devices.items() if h == api.UNHEALTHY]
    assert len(bad) == 1
    assert 3 in mgr.device_chips(bad[0])


def test_flip_emits_exactly_one_journal_event_each_way(node4):
    """Each healthy->unhealthy->healthy flip journals EXACTLY one
    event per transition, carrying device id and a human-readable
    reason — repeat sweeps in the same state must not re-emit."""
    mgr, _, hc = make(node4)
    node4.set_state(1, "health", "uncorrectable_ecc")
    hc.poll_once()
    hc.poll_once()  # same state again: no second event
    events = health_events()
    assert len(events) == 1, events
    assert events[0]["fields"]["device"] == "accel1"
    assert events[0]["fields"]["to"] == api.UNHEALTHY
    assert "UNCORRECTABLE_ECC" in events[0]["fields"]["reason"]

    node4.set_state(1, "health", "ok")
    hc.poll_once()
    hc.poll_once()
    events = health_events()
    assert len(events) == 2, events
    assert events[1]["fields"]["device"] == "accel1"
    assert events[1]["fields"]["to"] == api.HEALTHY
    assert events[1]["fields"]["reason"] == "chip health recovered"


def test_backend_failure_journals_each_device_once(node4):
    mgr, backend, hc = make(node4)

    def boom(chip):
        raise ChipBackendError("backend gone")

    backend.chip_health = boom
    hc.poll_once()
    hc.poll_once()  # already unhealthy: no re-emission
    events = health_events()
    assert len(events) == 4, events
    assert ({e["fields"]["device"] for e in events}
            == {"accel0", "accel1", "accel2", "accel3"})
    assert all("backend failure" in e["fields"]["reason"]
               for e in events)


def test_poll_records_sweep_span_and_histogram(node4):
    mgr, _, hc = make(node4)
    hc.poll_once()
    spans = [s for s in obs.TRACER.snapshot()["spans"]
             if s["name"] == "health.poll"]
    assert len(spans) == 1
    hist = obs.histogram("tpu_plugin_health_sweep_seconds")
    assert hist.count == 1


def test_listandwatch_latency_lands_in_histogram(node4):
    """The interceptor's connect->first-response latency for a REAL
    ListAndWatch stream lands in the per-method RPC histogram, and a
    health flip journals its transition while streaming."""
    from tests.plugin_helpers import ServingManager, short_tmpdir

    mgr, _, hc = make(node4)
    with ServingManager(mgr, short_tmpdir()) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            stream = stub.ListAndWatch(api.v1beta1_pb2.Empty(),
                                       timeout=10)
            first = next(iter(stream))
            assert {d.ID for d in first.devices} == {
                "accel0", "accel1", "accel2", "accel3"}
            node4.set_state(2, "health", "overheat")
            hc.poll_once()
            second = next(iter(stream))
            assert {d.ID: d.health for d in second.devices}[
                "accel2"] == api.UNHEALTHY
            stream.cancel()
    # Both API versions serve a ListAndWatch; this test drove the
    # v1beta1 stream, so at least that method's histogram must have
    # the observation.
    hists = [h for h in obs.TRACER.histograms()
             if h.name == "tpu_plugin_rpc_latency_seconds"
             and h.labels.get("method", "").endswith("ListAndWatch")]
    assert hists and any(h.count >= 1 for h in hists), [
        (h.labels, h.count) for h in hists]
    beta = [h for h in hists if "v1beta1" in h.labels["method"]]
    assert beta and beta[0].count >= 1
    events = health_events()
    assert len(events) == 1 and events[0]["fields"]["device"] == "accel2"


def test_start_stop_thread(node4):
    mgr, _, hc = make(node4)
    hc._interval = 0.05
    hc.start()
    node4.set_state(0, "health", "overheat")
    import time
    deadline = time.time() + 5
    while time.time() < deadline:
        if mgr.list_devices()["accel0"] == api.UNHEALTHY:
            break
        time.sleep(0.05)
    hc.stop()
    assert mgr.list_devices()["accel0"] == api.UNHEALTHY
