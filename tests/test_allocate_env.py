# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Allocate env contract -> real device runtime (VERDICT r2 #2).

The harness execs a child whose environment is exactly the plugin's
Allocate response and requires a non-CPU jitted step; with no TPU
reachable it exits EX_TEMPFAIL and the test skips (CI is CPU-only;
the TPU suite runs it for real and commits ALLOCATE_ENV_TPU.json).
"""

import json
import os
import subprocess
import sys

import pytest

from tests.conftest import REPO_ROOT

HARNESS = os.path.join(REPO_ROOT, "tools", "allocate_env_harness.py")


@pytest.mark.slow
def test_allocate_env_contract_boots_real_runtime():
    env = dict(os.environ, CEA_ALLOC_TIMEOUT_S="240")
    # The harness child must probe the real backend, not inherit the
    # test suite's CPU pin.
    env.pop("JAX_PLATFORMS", None)
    try:
        proc = subprocess.run(
            [sys.executable, HARNESS], env=env, timeout=600,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU backend unreachable (harness timed out)")
    if proc.returncode == 75:  # EX_TEMPFAIL: no TPU right now
        pytest.skip("no TPU reachable: " + proc.stderr.decode()[-200:])
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    line = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert line["ok"] is True
    artifact = json.load(open(os.path.join(REPO_ROOT,
                                           "ALLOCATE_ENV_TPU.json")))
    assert artifact["allocate_envs"]["TPU_VISIBLE_DEVICES"] == "0"
    assert artifact["child"]["contract_envs"]["TPU_WORKER_ID"] == "0"
    assert artifact["provenance"]["git_sha"]
