# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Shared helpers for plugin tests.

Reimplements the reference's test topology in Python: a KubeletStub
Registration service on a unix socket (alpha_plugin_test.go:35-69)
and a real gRPC loopback against the plugin's served socket
(beta_plugin_test.go:75-147).
"""

import os
import tempfile
import threading
from concurrent import futures

import grpc

from container_engine_accelerators_tpu.plugin import api


def short_tmpdir():
    """Unix socket paths must stay under ~108 chars; pytest tmp_path
    can exceed that, so sockets live in a short mkdtemp."""
    return tempfile.mkdtemp(prefix="tpu")


class KubeletStub(api.RegistrationServicer):
    """Fake kubelet Registration endpoint recording register calls."""

    def __init__(self, socket_path):
        self.socket_path = socket_path
        self.requests = []
        self.event = threading.Event()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        api.add_registration_v1beta1(self, self._server)
        api.add_registration_v1alpha(self, self._server)
        self._server.add_insecure_port(f"unix://{socket_path}")

    def Register(self, request, context):
        self.requests.append(request)
        self.event.set()
        # Same Empty message shape in both packages; pick by version.
        if request.version == api.V1BETA1_VERSION:
            return api.v1beta1_pb2.Empty()
        return api.v1alpha_pb2.Empty()

    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop(grace=0)


class ServingManager:
    """Runs manager.serve() in a thread and exposes client channels."""

    def __init__(self, manager, plugin_dir, kubelet_socket="kubelet.sock"):
        self.manager = manager
        self.plugin_dir = plugin_dir
        self.kubelet_socket = kubelet_socket
        self._thread = threading.Thread(
            target=manager.serve,
            args=(plugin_dir, kubelet_socket, "tpu"), daemon=True)

    def __enter__(self):
        self._thread.start()
        assert self.manager.wait_until_serving(10)
        return self

    def __exit__(self, *exc):
        self.manager.stop()
        self._thread.join(timeout=10)

    def socket_path(self):
        socks = [f for f in os.listdir(self.plugin_dir)
                 if f.startswith("tpu-") and f.endswith(".sock")]
        assert len(socks) == 1, socks
        return os.path.join(self.plugin_dir, socks[0])

    def channel(self):
        return grpc.insecure_channel(f"unix://{self.socket_path()}")
