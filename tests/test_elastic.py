# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Elastic training: checkpoint manager, eviction policy, supervisor.

The library counterpart of tools/chaos_check.py's multi-process
harness: everything here runs on the in-process 8-device CPU mesh, so
it is tier-1 cheap — resharded restore across mesh shapes, the
eviction policy's window hysteresis, the supervisor's
exactly-one-event contract, and the bounded coordinator init.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.models import MnistMLP
from container_engine_accelerators_tpu.models import mlp as mlp_mod
from container_engine_accelerators_tpu.parallel import (
    CheckpointManager,
    ElasticSupervisor,
    EvictionPolicy,
    FleetExhausted,
    MeshSpec,
    Trainer,
    build_mesh,
    reassign_shards,
    reshape_spec,
    restore_state,
    shard_assignment,
    state_payload,
)
from container_engine_accelerators_tpu.parallel.checkpoint import (
    CheckpointError,
    list_checkpoints,
)
from container_engine_accelerators_tpu.parallel.data import (
    synthetic_step_batch,
)
from container_engine_accelerators_tpu.parallel.elastic import (
    EVICTION_EVENT,
    RECOVERY_COUNTER,
    RESHAPE_EVENT,
    down_hosts_from_events,
)
from container_engine_accelerators_tpu.parallel.sharding import (
    batch_sharding,
)
from container_engine_accelerators_tpu.parallel.train import (
    cross_entropy_loss,
)


def _make_trainer(mesh, hidden=512, ema=0.0):
    model = MnistMLP(hidden=hidden, dtype=jnp.float32)
    trainer = Trainer(mlp_mod.make_apply_fn(model), cross_entropy_loss,
                      optax.sgd(0.1, momentum=0.9), mesh=mesh,
                      ema_decay=ema)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 28, 28, 1)))
    return model, trainer, variables


def _batch(step, mesh, batch=24):
    images, labels = synthetic_step_batch(step, batch, (28, 28, 1), 10,
                                          seed=7)
    sh = batch_sharding(mesh)
    return jax.device_put(images, sh), jax.device_put(labels, sh)


# -- checkpoint manager -----------------------------------------------

def test_resharded_restore_across_meshes(tmp_path):
    """Save under a 2x2 (data, model) mesh; restore under 1x2 and
    4x1: parameter-exact, and the optimizer's momentum reshards
    along with the params it mirrors."""
    devices = jax.devices()
    save_mesh = build_mesh(MeshSpec(data=2, model=2),
                           devices=devices[:4])
    _, trainer, variables = _make_trainer(save_mesh)
    state = trainer.init_state(variables)
    for step in range(2):
        state, _ = trainer.train_step(state, _batch(step, save_mesh))
    mgr = CheckpointManager(tmp_path)
    mgr.save(state_payload(state), step=int(state.step))
    mgr.wait_until_finished()
    assert mgr.latest_step() == 2
    assert mgr.manifest()["mesh_axes"] == {"data": 2, "model": 2}

    want_params = jax.tree_util.tree_map(np.asarray, state.params)
    want_opt = jax.tree_util.tree_map(np.asarray, state.opt_state)

    for spec, n_dev in ((MeshSpec(data=1, model=2), 2),
                        (MeshSpec(data=4, model=1), 4)):
        mesh = build_mesh(spec, devices=devices[:n_dev])
        _, new_trainer, _ = _make_trainer(mesh)
        template = new_trainer.init_state(variables)
        shardings = new_trainer.state_shardings(template)
        restored = restore_state(mgr, template, shardings=shardings)
        assert int(restored.step) == 2
        # Parameter-exact across the reshape...
        jax.tree_util.tree_map(
            lambda w, g: np.testing.assert_array_equal(
                w, np.asarray(g)), want_params, restored.params)
        # ...momentum travels with its params...
        jax.tree_util.tree_map(
            lambda w, g: np.testing.assert_array_equal(
                w, np.asarray(g)), want_opt, restored.opt_state)
        # ...and the layout is the RESTORING mesh's, not the saved
        # one's: every leaf sits on exactly the new mesh's devices.
        leaf = jax.tree_util.tree_leaves(restored.params)[0]
        assert {d.id for d in leaf.sharding.device_set} <= {
            d.id for d in mesh.devices.flat}
        # The restored state steps (shardings consistent end to end).
        state2, loss = new_trainer.train_step(restored,
                                              _batch(2, mesh))
        assert np.isfinite(float(loss))


def test_checkpoint_async_retention_and_listing(tmp_path):
    """Async saves land after wait_until_finished; keep=2 prunes;
    unfinished dirs (tmp siblings, meta-less) never count."""
    mesh = build_mesh(MeshSpec(data=8))
    _, trainer, variables = _make_trainer(mesh, hidden=32)
    state = trainer.init_state(variables)
    mgr = CheckpointManager(tmp_path, keep=2, goodput=trainer.goodput)
    for step in range(1, 5):
        state, _ = trainer.train_step(state, _batch(step, mesh))
        mgr.save(state_payload(state), step=step)
    mgr.wait_until_finished()
    assert mgr.steps() == [3, 4]
    (tmp_path / "checkpoint_9.tmp-1-0").mkdir()
    (tmp_path / "checkpoint_7").mkdir()  # no meta.json
    assert [s for s, _ in list_checkpoints(tmp_path)] == [3, 4]
    assert mgr.latest_step() == 4
    # The blocking snapshot was accounted to the checkpoint bucket.
    assert trainer.goodput.summary()["buckets"]["checkpoint"] > 0
    meta = mgr.manifest()
    assert meta["step"] == 4 and meta["bytes"] > 0
    assert any("['params']" in k for k in meta["keys"])


def test_checkpoint_background_failure_surfaces(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save({"x": jnp.ones((2,))}, step=1)
    mgr.wait_until_finished()

    def boom(arrays, meta, path):
        raise OSError("disk gone")

    mgr._write = boom
    mgr.save({"x": jnp.ones((2,))}, step=2)
    with pytest.raises(CheckpointError, match="disk gone"):
        mgr.wait_until_finished()


def test_checkpoint_save_after_close_raises(tmp_path):
    """A save racing (or following) close() must raise, not enqueue
    behind the shutdown sentinel where the exiting worker would drop
    it silently."""
    mgr = CheckpointManager(tmp_path)
    mgr.save({"x": jnp.ones((2,))}, step=1)
    mgr.close()
    assert mgr.latest_step() == 1
    with pytest.raises(CheckpointError, match="closed"):
        mgr.save({"x": jnp.ones((2,))}, step=2)
    assert mgr.latest_step() == 1


def test_checkpoint_partial_and_missing_leaves(tmp_path):
    mgr = CheckpointManager(tmp_path)
    payload = {"params": {"w": jnp.arange(4.0)},
               "opt_state": {"m": jnp.zeros((4,))}, "step": 3}
    mgr.save(payload, step=3, blocking=True)
    # Partial template (the serving loader's shape) restores cleanly.
    got = mgr.restore({"params": {"w": jnp.zeros((4,))}})
    np.testing.assert_array_equal(got["params"]["w"],
                                  np.arange(4.0))
    with pytest.raises(KeyError, match="no leaf"):
        mgr.restore({"params": {"nope": jnp.zeros(1)}})
    # missing="template" keeps the template's own leaf.
    got = mgr.restore({"params": {"nope": jnp.ones(1)}},
                      missing="template")
    np.testing.assert_array_equal(got["params"]["nope"], [1.0])


def test_restore_state_reseeds_ema_from_pre_ema_checkpoint(tmp_path):
    """A checkpoint written without EMA restores into an EMA-tracking
    run with the shadow re-seeded from the restored params."""
    mesh = build_mesh(MeshSpec(data=8))
    _, trainer, variables = _make_trainer(mesh, hidden=32)
    state = trainer.init_state(variables)
    state, _ = trainer.train_step(state, _batch(0, mesh))
    mgr = CheckpointManager(tmp_path)
    mgr.save(state_payload(state), step=1, blocking=True)
    assert not mgr.has_leaf("['ema_params']")

    _, ema_trainer, _ = _make_trainer(mesh, hidden=32, ema=0.9)
    template = ema_trainer.init_state(variables)
    restored = restore_state(
        mgr, template, shardings=ema_trainer.state_shardings(template))
    jax.tree_util.tree_map(
        lambda p, e: np.testing.assert_array_equal(np.asarray(p),
                                                   np.asarray(e)),
        restored.params, restored.ema_params)


# -- eviction policy --------------------------------------------------

def test_policy_skew_needs_consecutive_windows():
    policy = EvictionPolicy(skew_factor=1.5, skew_windows=3,
                            stale_after_s=5)
    assert policy.evaluate(skews={"h1": 2.0}) == []
    assert policy.evaluate(skews={"h1": 2.0}) == []
    assert policy.evaluate(skews={"h1": 2.0}) == [("h1", "straggler")]
    # Recovery resets the breach counter.
    assert policy.evaluate(skews={"h1": 1.0}) == []
    assert policy.evaluate(skews={"h1": 2.0}) == []


def test_policy_down_and_stale_are_immediate():
    policy = EvictionPolicy(skew_factor=2.0, skew_windows=3,
                            stale_after_s=5)
    assert policy.evaluate(down=["h2"]) == [("h2", "health_down")]
    assert policy.evaluate(stale={"h3": 6.0, "h4": 1.0}) == [
        ("h3", "host_hung")]


def test_policy_env_knobs(monkeypatch):
    monkeypatch.setenv("CEA_TPU_EVICT_SKEW", "3.5")
    monkeypatch.setenv("CEA_TPU_EVICT_WINDOWS", "1")
    monkeypatch.setenv("CEA_TPU_EVICT_STALE_S", "2")
    policy = EvictionPolicy()
    assert policy.skew_factor == 3.5
    assert policy.skew_windows == 1
    assert policy.stale_after_s == 2.0
    assert policy.evaluate(skews={"h0": 4.0}) == [("h0", "straggler")]
    with pytest.raises(ValueError):
        EvictionPolicy(skew_factor=1.0)


def test_down_hosts_from_health_events():
    events = [
        {"name": "health.transition", "unix": 1.0,
         "fields": {"device": "accel0", "to": "Unhealthy"}},
        {"name": "health.transition", "unix": 2.0,
         "fields": {"device": "accel1", "to": "Unhealthy"}},
        # accel0 recovered later: the LAST transition wins.
        {"name": "health.transition", "unix": 3.0,
         "fields": {"device": "accel0", "to": "Healthy"}},
        {"name": "other.event", "unix": 4.0, "fields": {}},
    ]
    mapping = {"accel0": "h0", "accel1": "h1"}
    assert down_hosts_from_events(events, mapping) == ["h1"]


def test_down_hosts_sibling_recovery_does_not_mask():
    """Last-transition-wins is per DEVICE: one chip of a host
    recovering must not clear the verdict for its still-down
    sibling."""
    events = [
        {"name": "health.transition", "unix": 1.0,
         "fields": {"device": "accel2", "to": "Unhealthy"}},
        {"name": "health.transition", "unix": 2.0,
         "fields": {"device": "accel3", "to": "Unhealthy"}},
        {"name": "health.transition", "unix": 3.0,
         "fields": {"device": "accel3", "to": "Healthy"}},
    ]
    mapping = {"accel2": "h1", "accel3": "h1"}
    assert down_hosts_from_events(events, mapping) == ["h1"]


# -- supervisor -------------------------------------------------------

def test_supervisor_exactly_one_event_per_failure():
    tracer = obs.Tracer(enabled=True)
    sup = ElasticSupervisor(
        hosts=["h0", "h1", "h2", "h3"], chips_per_host=2,
        model_parallel=2,
        policy=EvictionPolicy(skew_factor=1.5, skew_windows=2,
                              stale_after_s=5),
        tracer=tracer)
    assert sup.mesh_spec == MeshSpec(data=4, model=2)
    # One noisy skew window: no eviction yet.
    assert sup.observe(skews={"h2": 2.0}) is None
    plan = sup.observe(skews={"h2": 2.0})
    assert plan is not None
    assert plan.evicted == [("h2", "straggler")]
    assert plan.survivors == ["h0", "h1", "h3"]
    assert plan.mesh_spec == MeshSpec(data=3, model=2)
    assert plan.worker_ids == {"h0": 0, "h1": 1, "h3": 2}
    # h2's shard went to a survivor; everyone keeps their own.
    assert sorted(s for ss in plan.assignment.values()
                  for s in ss) == [0, 1, 2, 3]
    assert plan.assignment["h0"][:1] == [0]

    # A signal that keeps firing for the departed host is inert.
    assert sup.observe(skews={"h2": 9.9}) is None
    assert sup.observe(down=["h2"]) is None

    snap = tracer.snapshot()
    evictions = [e for e in snap["events"]
                 if e["name"] == EVICTION_EVENT]
    reshapes = [e for e in snap["events"]
                if e["name"] == RESHAPE_EVENT]
    assert len(evictions) == 1 and len(reshapes) == 1
    assert evictions[0]["fields"]["host"] == "h2"
    assert reshapes[0]["fields"]["old_shape"] == "4x2"
    assert reshapes[0]["fields"]["new_shape"] == "3x2"
    counters = tracer.counters()
    assert counters[(RECOVERY_COUNTER,
                     (("reason", "straggler"),))] == 1

    # Second failure -> second (single) event pair.
    plan2 = sup.observe(down=["h0"])
    assert plan2.evicted == [("h0", "health_down")]
    assert plan2.mesh_spec == MeshSpec(data=2, model=2)
    snap = tracer.snapshot()
    assert len([e for e in snap["events"]
                if e["name"] == EVICTION_EVENT]) == 2
    assert len([e for e in snap["events"]
                if e["name"] == RESHAPE_EVENT]) == 2

    with pytest.raises(FleetExhausted):
        sup.evict([("h1", "health_down"), ("h3", "health_down")])


def test_supervisor_model_axis_fallback_to_1d():
    sup = ElasticSupervisor(hosts=["h0", "h1", "h2"],
                            chips_per_host=1, model_parallel=3,
                            tracer=obs.Tracer(enabled=False))
    assert sup.mesh_spec == MeshSpec(data=1, model=3)
    plan = sup.evict([("h1", "health_down")])
    # 2 chips do not fold onto model=3: 1-D fallback.
    assert plan.mesh_spec == MeshSpec(data=2, model=1)


def test_supervisor_recovery_accounting():
    from container_engine_accelerators_tpu.obs.efficiency import (
        GoodputLedger,
        ledger_from_snapshot,
    )

    tracer = obs.Tracer(enabled=True)
    ledger = GoodputLedger()
    ledger.set_wall(10.0)  # the books rescale against real wall
    sup = ElasticSupervisor(hosts=["h0", "h1"], goodput=ledger,
                            tracer=tracer)
    plan = sup.evict([("h1", "health_down")])
    sup.complete_recovery(plan, 1.25, resume_step=40)
    assert plan.resume_step == 40
    assert ledger.summary()["buckets"]["restart"] == pytest.approx(
        1.25, abs=1e-6)
    # The offline replay attributes the same event shape identically
    # (synthetic snapshot: the replay's wall is the journal window,
    # so give the episode a realistic one).
    event = next(e for e in tracer.snapshot()["events"]
                 if e["name"] == "train.recovered")
    assert event["fields"]["recovery_s"] == pytest.approx(1.25)
    snap = {
        "spans": [{"name": "train.step_run", "start_unix": 100.0,
                   "duration_s": 8.0}],
        "events": [{"name": "train.recovered", "unix": 109.25,
                    "fields": dict(event["fields"])}],
    }
    replayed = ledger_from_snapshot(snap).summary()
    assert replayed["buckets"]["restart"] == pytest.approx(1.25,
                                                           rel=1e-3)
    assert replayed["buckets"]["productive"] == pytest.approx(
        8.0, rel=1e-3)


def test_supervisor_in_process_rebuild_matches_uninterrupted(
        tmp_path):
    """The tier-1 chaos story: train 4 "hosts" x 2 chips, checkpoint,
    evict one host, rebuild 4x2 -> 3x2 via the supervisor, resume
    resharded — and land on the SAME loss as the uninterrupted run
    (deterministic step-keyed global batches make the trajectory
    mesh-layout-independent)."""
    devices = jax.devices()
    mesh = build_mesh(MeshSpec(data=4, model=2))
    _, trainer, variables = _make_trainer(mesh, hidden=128)
    state = trainer.init_state(variables)
    mgr = CheckpointManager(tmp_path, goodput=trainer.goodput)
    for step in range(3):
        state, _ = trainer.train_step(state, _batch(step, mesh))
    mgr.save(state_payload(state), step=int(state.step))

    # Uninterrupted reference: continue on the full fleet.
    ref_state = state
    for step in range(3, 6):
        ref_state, ref_loss = trainer.train_step(
            ref_state, _batch(step, mesh))

    sup = ElasticSupervisor(
        hosts=["h0", "h1", "h2", "h3"], chips_per_host=2,
        model_parallel=2, goodput=trainer.goodput,
        tracer=obs.Tracer(enabled=False),
        host_devices={f"h{i}": devices[2 * i:2 * i + 2]
                      for i in range(4)})
    plan = sup.observe(down=["h1"])
    mgr.wait_until_finished()
    new_trainer, new_state, new_mesh = sup.rebuild(
        plan, trainer, mgr,
        init_state=lambda t: t.init_state(variables))
    assert dict(new_mesh.shape) == {"data": 3, "model": 2}
    assert int(new_state.step) == 3
    assert plan.resume_step == 3
    for step in range(3, 6):
        new_state, loss = new_trainer.train_step(
            new_state, _batch(step, new_mesh))
    assert float(loss) == pytest.approx(float(ref_loss), abs=1e-5)
    # Recovery landed in the shared ledger's restart bucket.
    assert trainer.goodput.summary()["buckets"]["restart"] > 0


def test_supervisor_rebuild_before_first_checkpoint(tmp_path):
    """An eviction before any checkpoint has landed must not wedge
    recovery: rebuild() falls back to the fresh init template (step
    0) instead of raising FileNotFoundError."""
    devices = jax.devices()
    mesh = build_mesh(MeshSpec(data=4, model=2))
    _, trainer, variables = _make_trainer(mesh, hidden=32)
    mgr = CheckpointManager(tmp_path, goodput=trainer.goodput)
    sup = ElasticSupervisor(
        hosts=["h0", "h1", "h2", "h3"], chips_per_host=2,
        model_parallel=2, goodput=trainer.goodput,
        tracer=obs.Tracer(enabled=False),
        host_devices={f"h{i}": devices[2 * i:2 * i + 2]
                      for i in range(4)})
    plan = sup.observe(down=["h1"])
    new_trainer, new_state, new_mesh = sup.rebuild(
        plan, trainer, mgr,
        init_state=lambda t: t.init_state(variables))
    assert dict(new_mesh.shape) == {"data": 3, "model": 2}
    assert int(new_state.step) == 0
    assert plan.resume_step == 0
    _, loss = new_trainer.train_step(new_state, _batch(0, new_mesh))
    assert np.isfinite(float(loss))


def test_snapshot_copies_host_resident_leaves(tmp_path):
    """The blocking snapshot must not hand the background writer a
    view into the caller's live buffer: a host numpy leaf mutated in
    place after save() returns must not leak into the archive."""
    mgr = CheckpointManager(tmp_path)
    host_leaf = np.arange(8, dtype=np.float32)
    arrays, _ = mgr._snapshot({"w": host_leaf}, step=1)
    (key,) = arrays
    assert not np.shares_memory(arrays[key], host_leaf)
    host_leaf += 100.0
    np.testing.assert_array_equal(
        arrays[key], np.arange(8, dtype=np.float32))


# -- data shard reassignment ------------------------------------------

def test_shard_assignment_and_reassign():
    assignment = shard_assignment(8, ["h0", "h1", "h2", "h3"])
    assert assignment == {"h0": [0, 1], "h1": [2, 3], "h2": [4, 5],
                          "h3": [6, 7]}
    after = reassign_shards(assignment, ["h2"])
    # Survivors keep their own shards in order; orphans spread.
    assert after["h0"][:2] == [0, 1]
    assert after["h1"][:2] == [2, 3]
    assert after["h3"][:2] == [6, 7]
    assert sorted(s for ss in after.values() for s in ss) == list(
        range(8))
    # Load spread stays within one shard.
    sizes = sorted(len(s) for s in after.values())
    assert sizes[-1] - sizes[0] <= 1
    with pytest.raises(ValueError):
        reassign_shards(assignment, ["h0", "h1", "h2", "h3"])
    with pytest.raises(ValueError):
        shard_assignment(2, ["h0", "h1", "h2"])
    uneven = shard_assignment(5, ["h0", "h1"])
    assert [len(uneven[h]) for h in ("h0", "h1")] == [3, 2]


def test_synthetic_step_batch_deterministic():
    a = synthetic_step_batch(4, 8, (2, 2, 1), 10, seed=1)
    b = synthetic_step_batch(4, 8, (2, 2, 1), 10, seed=1)
    c = synthetic_step_batch(5, 8, (2, 2, 1), 10, seed=1)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    assert not np.array_equal(a[0], c[0])


# -- bounded coordinator init -----------------------------------------

def test_initialize_retries_then_deadline(monkeypatch):
    from container_engine_accelerators_tpu.parallel.distributed import (
        DeadlineExceeded,
        initialize_from_plugin_env,
    )

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "hostA,hostB")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    calls = []

    def failing(**kwargs):
        calls.append(kwargs)
        raise RuntimeError("connection refused")

    before = dict(obs.TRACER.counters())
    with pytest.raises(DeadlineExceeded, match="after 3 attempt"):
        initialize_from_plugin_env(timeout_ms=1000, retries=2,
                                   backoff_ms=1, _initialize=failing)
    assert len(calls) == 3
    assert calls[0]["coordinator_address"].startswith("hostA:")
    assert calls[0]["initialization_timeout"] == 1
    after = obs.TRACER.counters()

    def delta(reason):
        key = ("tpu_train_recovery_total", (("reason", reason),))
        return after.get(key, 0) - before.get(key, 0)

    assert delta("coordinator_retry") == 2
    assert delta("coordinator_timeout") == 1


def test_initialize_succeeds_after_transient_failure(monkeypatch):
    from container_engine_accelerators_tpu.parallel.distributed import (
        initialize_from_plugin_env,
    )

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "hostA,hostB")
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("CEA_COORDINATOR_ADDRESS", "127.0.0.1:1")
    attempts = []

    def flaky(**kwargs):
        attempts.append(kwargs)
        if len(attempts) == 1:
            raise RuntimeError("transient")

    assert initialize_from_plugin_env(
        timeout_ms=1000, retries=2, backoff_ms=1,
        _initialize=flaky) is True
    assert len(attempts) == 2
    assert attempts[0]["coordinator_address"] == "127.0.0.1:1"


def test_initialize_env_knob_parsing(monkeypatch):
    from container_engine_accelerators_tpu.parallel.distributed import (
        DeadlineExceeded,
        initialize_from_plugin_env,
    )

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "hostA,hostB")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("CEA_TPU_COORD_TIMEOUT_MS", "2000")
    monkeypatch.setenv("CEA_TPU_COORD_RETRIES", "0")
    monkeypatch.setenv("CEA_TPU_COORD_BACKOFF_MS", "1")
    calls = []

    def failing(**kwargs):
        calls.append(kwargs)
        raise RuntimeError("nope")

    with pytest.raises(DeadlineExceeded):
        initialize_from_plugin_env(_initialize=failing)
    assert len(calls) == 1
    assert calls[0]["initialization_timeout"] == 2
    # Single-host slice stays a no-op regardless of knobs.
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "solo")
    assert initialize_from_plugin_env(_initialize=failing) is False


# -- diagnose provenance ----------------------------------------------

def test_latest_meta_reads_without_jax_arrays(tmp_path):
    """latest_meta is the diagnose bundle's checkpoint-provenance
    reader: plain json, survives a corrupt meta without raising."""
    from container_engine_accelerators_tpu.parallel.checkpoint import (
        latest_meta,
    )

    assert latest_meta(tmp_path) is None
    mgr = CheckpointManager(tmp_path)
    mgr.save({"x": jnp.ones((2,))}, step=5, blocking=True)
    meta = latest_meta(tmp_path)
    assert meta["step"] == 5
    assert meta["path"].endswith("checkpoint_5")
    assert meta["keys"] == ["['x']"]
    (tmp_path / "checkpoint_6").mkdir()
    (tmp_path / "checkpoint_6" / "meta.json").write_text("{broken")
    bad = latest_meta(tmp_path)
    assert "error" in bad and bad["path"].endswith("checkpoint_6")
