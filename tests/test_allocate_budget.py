# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Allocate-latency budget (BASELINE.md metric #2, tracked in CI).

The scheduling-critical RPC (SURVEY.md section 3.2; the reference's
beta_plugin.go:54-88 path) must stay in-memory-fast: map lookups +
proto marshalling, no I/O. The budget is deliberately loose for noisy
CI machines — its job is to catch an accidental O(n^3) or filesystem
read landing on the Allocate path, not to benchmark. The tracked
artifact lives in ALLOC_BENCH.json (tools/bench_allocate.py).
"""

import json
import os
import subprocess
import sys

from tests.conftest import REPO_ROOT

P50_BUDGET_US = 5000
P95_BUDGET_US = 25000


def test_allocate_latency_within_budget():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "bench_allocate.py"),
         "--iterations", "300", "--warmup", "50"],
        check=True, capture_output=True, timeout=240, text=True)
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith("{")][-1]
    result = json.loads(line)
    assert result["p50_us"] < P50_BUDGET_US, result
    assert result["p95_us"] < P95_BUDGET_US, result


def test_alloc_bench_artifact_tracked():
    """The committed artifact must exist and parse (round-over-round
    tracking; round-1 verdict item 5)."""
    path = os.path.join(REPO_ROOT, "ALLOC_BENCH.json")
    with open(path) as f:
        artifact = json.load(f)
    assert artifact["result"]["metric"] == "allocate_latency"
    assert artifact["result"]["p50_us"] > 0
