# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Inference-server tests over real HTTP (serving demo parity)."""

import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import MnistMLP
from container_engine_accelerators_tpu.models import mlp as mlp_mod
from container_engine_accelerators_tpu.serving import InferenceServer

# Tier-1 budget: this module compiles many distinct XLA programs and
# runs minutes on the CI CPU mesh. It only became collectable when the
# shard_map compat shim fixed the jax-version import error, and
# including it would blow the 870s tier-1 cap — so it runs in the full
# lane (`make test` / pytest without `-m "not slow"`) instead.
pytestmark = pytest.mark.slow



@pytest.fixture(scope="module")
def server():
    model = MnistMLP(hidden=32, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    srv = InferenceServer("mnist", mlp_mod.make_apply_fn(model), variables,
                          (28, 28, 1), port=0, max_batch=4, max_wait_ms=2)
    srv.start()
    yield srv
    srv.stop()


def post(server, path, payload):
    req = urllib.request.Request(
        f"http://localhost:{server.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_predict(server):
    instance = np.zeros((28, 28, 1)).tolist()
    out = post(server, "/v1/models/mnist:predict", {"instances": [instance]})
    assert len(out["predictions"]) == 1
    pred = out["predictions"][0]
    assert 0 <= pred["class"] < 10
    assert 0.0 <= pred["score"] <= 1.0


def test_healthz_and_stats(server):
    with urllib.request.urlopen(
            f"http://localhost:{server.port}/healthz", timeout=10) as resp:
        assert json.loads(resp.read())["status"] == "ok"
    # Issue a request of our own: the module fixture is shared, and
    # counting on earlier tests' traffic makes this fail when run
    # alone (pytest tests/test_serving.py::test_healthz_and_stats).
    post(server, "/v1/models/mnist:predict",
         {"instances": [np.zeros((28, 28, 1)).tolist()]})
    with urllib.request.urlopen(
            f"http://localhost:{server.port}/stats", timeout=10) as resp:
        stats = json.loads(resp.read())
    assert stats["requests"] >= 1


def test_bad_shape_rejected(server):
    with pytest.raises(urllib.error.HTTPError) as err:
        post(server, "/v1/models/mnist:predict",
             {"instances": [np.zeros((4, 4)).tolist()]})
    assert err.value.code == 400


def test_unknown_model_404(server):
    with pytest.raises(urllib.error.HTTPError) as err:
        post(server, "/v1/models/nope:predict", {"instances": []})
    assert err.value.code == 404


def test_malformed_body_400(server):
    req = urllib.request.Request(
        f"http://localhost:{server.port}/v1/models/mnist:predict",
        data=b"{not json", headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req, timeout=10)
    assert err.value.code == 400


def test_concurrent_batching(server):
    import threading
    instance = np.zeros((28, 28, 1)).tolist()
    results = []

    def call():
        out = post(server, "/v1/models/mnist:predict",
                   {"instances": [instance]})
        results.append(out["predictions"][0]["class"])

    threads = [threading.Thread(target=call) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 8
    assert len(set(results)) == 1  # same input -> same class


@pytest.fixture(scope="module")
def lm_server():
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import GenerationServer

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=4)
    srv.start()
    yield srv
    srv.stop()


def test_generate(lm_server):
    out = post(lm_server, "/v1/models/lm:generate",
               {"prompts": [[1, 2, 3, 4]], "max_new_tokens": 6})
    seqs = out["sequences"]
    assert len(seqs) == 1 and len(seqs[0]) == 10
    assert seqs[0][:4] == [1, 2, 3, 4]
    assert all(0 <= t < 64 for t in seqs[0])


def test_generate_sampling_and_batch(lm_server):
    out = post(lm_server, "/v1/models/lm:generate",
               {"prompts": [[5, 6], [7, 8]], "max_new_tokens": 4,
                "temperature": 1.0})
    assert len(out["sequences"]) == 2
    assert all(len(s) == 6 for s in out["sequences"])


def test_generate_span_tree_on_debug_trace(lm_server):
    """One generate request produces a nested span tree — request ->
    admission/wait on the handler thread, with the engine thread's
    admission prefill and decode steps parented across threads into
    the same trace — retrievable from the serving port's own
    /debug/trace, with the request latency in the
    serving_request_latency_seconds histogram and the per-step
    occupancy in tpu_serving_slot_occupancy."""
    from container_engine_accelerators_tpu import obs

    obs.TRACER.reset()
    post(lm_server, "/v1/models/lm:generate",
         {"prompts": [[1, 2, 3]], "max_new_tokens": 4})
    with urllib.request.urlopen(
            f"http://localhost:{lm_server.port}/debug/trace",
            timeout=10) as resp:
        trace = json.loads(resp.read())
    spans = {}
    for s in trace["spans"]:
        spans.setdefault(s["name"], s)
    for name in ("serving.request", "serving.admission",
                 "serving.wait", "serving.prefill",
                 "serving.engine_step"):
        assert name in spans, sorted(spans)
    req = spans["serving.request"]
    assert spans["serving.prefill"]["trace_id"] == req["trace_id"]
    assert spans["serving.engine_step"]["trace_id"] == req["trace_id"]
    assert spans["serving.engine_step"]["attrs"]["slots_active"] >= 1
    assert not trace["open_spans"]
    text = obs.prometheus_text(obs.TRACER)
    assert "serving_request_latency_seconds_bucket" in text
    assert "tpu_serving_slot_occupancy_bucket" in text


def test_debug_requests_endpoint(lm_server):
    """/debug/requests over real HTTP: engine-mode servers dump the
    retired attribution ring (balanced records, ?n= honored); /stats
    carries latency_attribution + the saturation plane. The
    service-level contracts live in test_slo_attribution.py."""
    post(lm_server, "/v1/models/lm:generate",
         {"prompts": [[2, 4, 6]], "max_new_tokens": 4})
    with urllib.request.urlopen(
            f"http://localhost:{lm_server.port}/debug/requests?n=1",
            timeout=10) as resp:
        payload = json.loads(resp.read())
    assert payload["retired_total"] >= 1
    assert len(payload["records"]) == 1
    rec = payload["records"][0]
    assert rec["outcome"] == "completed"
    assert abs(sum(rec["buckets"].values()) - rec["wall_s"]) \
        <= max(0.01 * rec["wall_s"], 2e-5)
    with urllib.request.urlopen(
            f"http://localhost:{lm_server.port}/stats",
            timeout=10) as resp:
        stats = json.loads(resp.read())
    assert "latency_attribution" in stats
    assert 0.0 <= stats["saturation"]["max"] <= 1.0


def test_debug_requests_404_off_engine(server):
    """Non-engine servers (here: the image InferenceServer) have no
    attribution ring — the endpoint 404s instead of faking one."""
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(
            f"http://localhost:{server.port}/debug/requests",
            timeout=10)
    assert err.value.code == 404


def test_generate_cross_request_sharing_on_engine():
    """Concurrent generate requests — different temperatures,
    different true prompt lengths, different BUCKETS — share the one
    slot pool: both come back correct and /stats reports the engine's
    occupancy fields (batch_occupancy_avg, slots_active, queue
    depth). Requests arriving while the pool is mid-decode admit
    in-flight instead of waiting a batch boundary, so the pool sees
    multi-row steps whenever lifetimes overlap."""
    import threading

    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=4)
    assert srv._engine_service is not None
    srv.start()
    try:
        results = {}

        def fire(tag, prompt, temp):
            results[tag] = post(
                srv, "/v1/models/lm:generate",
                {"prompts": [prompt], "max_new_tokens": 8,
                 "temperature": temp})

        threads = [
            threading.Thread(target=fire, args=("a", [1, 2, 3], 0.7)),
            threading.Thread(target=fire,
                             args=("b", [4, 5, 6, 7], 1.3)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results["a"]["sequences"][0]) == 11
        assert results["a"]["sequences"][0][:3] == [1, 2, 3]
        assert len(results["b"]["sequences"][0]) == 12
        assert results["b"]["sequences"][0][:4] == [4, 5, 6, 7]
        with urllib.request.urlopen(
                f"http://localhost:{srv.port}/stats",
                timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["engine_steps"] >= 1
        assert stats["rows_decoded"] >= stats["engine_steps"]
        assert stats["batch_occupancy_avg"] is not None
        assert stats["avg_batch_occupancy"] \
            == stats["batch_occupancy_avg"]
        assert stats["slots_active"] == 0
        assert stats["slots_free"] == 4
        assert stats["queue_depth"] == 0
        assert stats["requests_retired"] == 2
    finally:
        srv.stop()


@pytest.mark.slow
def test_train_checkpoint_serve_roundtrip(tmp_path):
    """The full loop: train.py writes a checkpoint, serve.py's loader
    restores it, and the served logits come from the TRAINED weights
    (different greedy text than fresh init would produce is too
    flaky to assert; instead compare restored params to the
    checkpoint exactly)."""
    import importlib.util

    import numpy as onp

    spec = importlib.util.spec_from_file_location(
        "demo_train_roundtrip", "demo/tpu-training/train.py")
    train_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(train_mod)
    train_mod.main([
        "--model", "transformer", "--num-layers", "2",
        "--embed-dim", "32", "--num-heads", "4", "--seq-len", "16",
        "--vocab-size", "64", "--batch-size", "16", "--steps", "2",
        "--warmup-steps", "0", "--model-dir", str(tmp_path)])

    spec2 = importlib.util.spec_from_file_location(
        "demo_serve_roundtrip", "demo/serving/serve.py")
    serve_mod = importlib.util.module_from_spec(spec2)
    spec2.loader.exec_module(serve_mod)
    from container_engine_accelerators_tpu.models import TransformerLM

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=16)
    init_vars = {"params": model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]}
    restored = serve_mod.load_checkpoint_variables(
        str(tmp_path), init_vars)

    # Independent read of what train.py wrote: the raw npz archive,
    # not the library reader the serving loader itself uses.
    names = sorted(n for n in tmp_path.iterdir()
                   if n.name.startswith("checkpoint_"))
    flat, _ = jax.tree_util.tree_flatten_with_path(
        {"params": restored["params"]})
    assert flat
    with onp.load(names[-1] / "arrays.npz") as raw:
        for path, got in flat:
            key = jax.tree_util.keystr(path)
            onp.testing.assert_array_equal(onp.asarray(got), raw[key])
    # And they differ from a fresh init (training moved them).
    fresh = jax.tree_util.tree_leaves(init_vars["params"])
    assert any(not onp.array_equal(onp.asarray(g), onp.asarray(f))
               for g, f in zip(got, fresh))


def test_generate_warm_compiles_engine_programs():
    """warm=True (engine mode) runs one warm request per bucket
    through the slot engine — compiling every prefill program plus
    the insert/step pair — then resets the occupancy counters so
    /stats describes real traffic only."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=2,
                           buckets=[8, 16], warm=True)
    assert srv._ready.is_set()
    assert srv.stats()["engine_prefills"] == 0  # warm traffic reset
    srv.start()
    try:
        out = post(srv, "/v1/models/lm:generate",
                   {"prompts": [[1, 2, 3]], "max_new_tokens": 2})
        assert len(out["sequences"][0]) == 5
        assert srv.stats()["engine_prefills"] == 1
    finally:
        srv.stop()


def test_engine_honors_exact_top_k():
    """The engine's per-row top_k is traced data, not a compiled
    shape, so the client's EXACT k applies (no power-of-two
    quantization): top_k=1 sampling is a point mass and must
    reproduce greedy output token-for-token — proof the filter
    reached the step program unquantized."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer(
        "lm", model, params, port=0, max_new_tokens=8, max_batch=2,
        buckets=[8], warm=True,
        warm_filters=[{"top_k": 3, "top_p": 0.9}])  # accepted, inert
    assert srv._ready.is_set()
    srv.start()
    try:
        greedy = post(srv, "/v1/models/lm:generate",
                      {"prompts": [[1, 2, 3]], "max_new_tokens": 6})
        topk1 = post(srv, "/v1/models/lm:generate",
                     {"prompts": [[1, 2, 3]], "max_new_tokens": 6,
                      "temperature": 1.0, "top_k": 1})
        assert greedy["sequences"] == topk1["sequences"]
    finally:
        srv.stop()


def test_generate_async_warm_gates_healthz():
    """warm_async=True: /healthz answers 503 while programs compile
    and 200 after — the readinessProbe contract that keeps an HPA
    replica out of the Service until no request would pay a compile."""
    import time
    import urllib.error

    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=2,
                           buckets=[8, 16], warm=True, warm_async=True)
    srv.start()
    try:
        url = f"http://localhost:{srv.port}/healthz"
        # The HTTP server answers immediately; readiness may not.
        if not srv._ready.is_set():
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url, timeout=10)
            assert err.value.code == 503
            assert json.loads(err.value.read())["status"] == "warming"
        deadline = time.monotonic() + 120
        while not srv._ready.is_set():
            assert time.monotonic() < deadline, "warm-up never finished"
            time.sleep(0.1)
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert json.loads(resp.read())["status"] == "ok"
        out = post(srv, "/v1/models/lm:generate",
                   {"prompts": [[1, 2, 3]], "max_new_tokens": 2})
        assert len(out["sequences"][0]) == 5
    finally:
        srv.stop()


def test_generate_top_k_top_p(lm_server):
    out = post(lm_server, "/v1/models/lm:generate",
               {"prompts": [[5, 6, 7]], "max_new_tokens": 4,
                "temperature": 0.9, "top_k": 4, "top_p": 0.8})
    seq = out["sequences"][0]
    assert len(seq) == 7 and seq[:3] == [5, 6, 7]
    assert all(0 <= t < 64 for t in seq)


def test_generate_validation(lm_server):
    for payload in (
            {"prompts": []},
            {"prompts": [[1, 2], [1, 2, 3]]},          # ragged
            {"prompts": [[1]], "max_new_tokens": 999},  # over limit
            {"prompts": [[0] * 30], "max_new_tokens": 8},  # > max_seq
            {"prompts": [[1]], "top_k": -1, "temperature": 1.0},
            {"prompts": [[1]], "top_p": 0.0, "temperature": 1.0},
            {"prompts": [[1]], "top_k": 5},  # filters need temp > 0
            {"prompts": [[1]], "eos_id": 64},  # >= vocab
            {"prompts": [[1]], "eos_id": -2},
            # Negative temp must 400 here — reaching the engine it
            # would poison the step's per-row temperature vector.
            {"prompts": [[1]], "temperature": -1.0},
            {"prompts": [[1]], "temperature": float("nan")},
    ):
        with pytest.raises(urllib.error.HTTPError) as err:
            post(lm_server, "/v1/models/lm:generate", payload)
        assert err.value.code == 400


def test_generate_tensor_parallel_params():
    """A GenerationServer whose params are sharded over a model axis
    (serve.py --tensor-parallel) must produce exactly the greedy
    sequences of the replicated server — GSPMD propagates the param
    shardings through decode's scan and KV cache."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.models.decode import decode
    from container_engine_accelerators_tpu.parallel import build_mesh
    from container_engine_accelerators_tpu.parallel.mesh import MeshSpec
    from container_engine_accelerators_tpu.parallel.sharding import (
        param_shardings,
    )
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    # embed_dim >= the sharding width threshold so kernels do shard.
    model = TransformerLM(vocab_size=512, embed_dim=512, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    mesh = build_mesh(MeshSpec(data=1, model=4))
    shardings = param_shardings(mesh, params)
    specs = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda s: s.spec, shardings,
                               is_leaf=lambda x: hasattr(x, "spec")))
    assert any(any(a is not None for a in s) for s in specs), \
        "no param sharded; the test would not exercise TP"
    params_tp = jax.device_put(params, shardings)

    prompt = [1, 2, 3, 4]
    want = np.asarray(decode(
        model, params, jnp.asarray([prompt], jnp.int32), 6))

    srv = GenerationServer("lm-tp", model, params_tp, port=0,
                           max_new_tokens=8, max_batch=4)
    srv.start()
    try:
        out = post(srv, "/v1/models/lm-tp:generate",
                   {"prompts": [prompt], "max_new_tokens": 6})
        assert out["sequences"][0] == want[0, :10].tolist()
    finally:
        srv.stop()


def test_model_status_endpoint(lm_server):
    """GET /v1/models/<name> — TF-Serving model-status parity, with
    the generation limits a client needs to shape requests."""
    import urllib.request

    with urllib.request.urlopen(
            f"http://localhost:{lm_server.port}/v1/models/lm",
            timeout=30) as resp:
        out = json.loads(resp.read())
    status = out["model_version_status"][0]
    assert status["state"] == "AVAILABLE"
    meta = status["metadata"]
    assert meta["kind"] == "generate"
    assert meta["vocab_size"] == 64
    assert meta["max_batch"] == 4
    assert meta["prompt_buckets"] == sorted(meta["prompt_buckets"])


def test_generate_repetition_penalty(lm_server):
    out = post(lm_server, "/v1/models/lm:generate",
               {"prompts": [[3, 9, 3]], "max_new_tokens": 6,
                "repetition_penalty": 5.0})
    assert len(out["sequences"][0]) == 9
    with pytest.raises(urllib.error.HTTPError) as err:
        post(lm_server, "/v1/models/lm:generate",
             {"prompts": [[1]], "repetition_penalty": 0})
    assert err.value.code == 400


def test_generate_logprobs(lm_server):
    out = post(lm_server, "/v1/models/lm:generate",
               {"prompts": [[2, 4, 6]], "max_new_tokens": 5,
                "logprobs": True})
    assert len(out["sequences"][0]) == 8
    lp = out["logprobs"][0]
    assert len(lp) == 8
    assert lp[0] == 0.0
    assert all(x <= 0.0 for x in lp)


def test_scoring_mode(lm_server):
    """max_new_tokens 0 + logprobs = pure prompt scoring
    (perplexity) through the same decode program."""
    out = post(lm_server, "/v1/models/lm:generate",
               {"prompts": [[2, 4, 6, 8]], "max_new_tokens": 0,
                "logprobs": True})
    assert out["sequences"][0] == [2, 4, 6, 8]
    lp = out["logprobs"][0]
    assert len(lp) == 4 and lp[0] == 0.0
    assert all(x < 0.0 for x in lp[1:])
    with pytest.raises(urllib.error.HTTPError) as err:
        post(lm_server, "/v1/models/lm:generate",
             {"prompts": [[1, 2]], "max_new_tokens": 0})
    assert err.value.code == 400


def test_generate_mixed_traffic_stress(lm_server):
    """Concurrent requests spanning buckets, sampling modes,
    filters, penalties, logprobs, and scoring must all succeed with
    correctly-shaped responses — the engine's full per-row knob
    space under real thread interleaving."""
    payloads = [
        {"prompts": [[1, 2]], "max_new_tokens": 3},
        {"prompts": [[3, 4, 5, 6, 7]], "max_new_tokens": 4,
         "temperature": 1.0, "top_k": 4},
        {"prompts": [[8]], "max_new_tokens": 2, "temperature": 0.7,
         "top_p": 0.9, "repetition_penalty": 1.3},
        {"prompts": [[9, 10, 11]], "max_new_tokens": 3,
         "logprobs": True},
        {"prompts": [[12, 13]], "max_new_tokens": 0,
         "logprobs": True},
        {"prompts": [[14, 15, 16]], "max_new_tokens": 5,
         "temperature": 1.2, "min_p": 0.05, "eos_id": 7},
    ]
    results = [None] * (len(payloads) * 3)

    def call(idx, payload):
        out = post(lm_server, "/v1/models/lm:generate", payload)
        p_len = len(payload["prompts"][0])
        want = p_len + payload["max_new_tokens"]
        ok = len(out["sequences"][0]) == want
        if payload.get("logprobs"):
            ok &= len(out["logprobs"][0]) == want
        results[idx] = ok

    threads = [threading.Thread(target=call, args=(i, payloads[i % len(payloads)]))
               for i in range(len(results))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(results), results


def test_text_serving_byte_tokenizer():
    """Text in, text out through the byte tokenizer: encode ->
    decode round trip plus server-level completions."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )
    from container_engine_accelerators_tpu.serving.tokenizer import (
        ByteTokenizer,
        load_tokenizer,
    )

    tok = ByteTokenizer()
    assert tok.decode(tok.encode("héllo wörld")) == "héllo wörld"
    assert isinstance(load_tokenizer("byte"), ByteTokenizer)

    model = TransformerLM(vocab_size=300, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm-text", model, params, port=0,
                           max_new_tokens=8, max_batch=4,
                           tokenizer=tok)
    srv.start()
    try:
        out = post(srv, "/v1/models/lm-text:generate",
                   {"text": ["hi"], "max_new_tokens": 4})
        assert out["sequences"][0][:2] == [104, 105]  # 'h', 'i'
        assert isinstance(out["completions"][0], str)

        with pytest.raises(urllib.error.HTTPError) as err:
            post(srv, "/v1/models/lm-text:generate",
                 {"text": ["hi"], "prompts": [[1]]})
        assert err.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as err:
            post(srv, "/v1/models/lm-text:generate", {"text": [""]})
        assert err.value.code == 400
    finally:
        srv.stop()


def test_text_serving_requires_tokenizer(lm_server):
    with pytest.raises(urllib.error.HTTPError) as err:
        post(lm_server, "/v1/models/lm:generate",
             {"text": ["hello"], "max_new_tokens": 2})
    assert err.value.code == 400


def test_backpressure_sheds_load():
    """A full admission queue must yield immediate shed (None ->
    503), not unbounded queueing; accepted work still completes."""
    import time as _time

    from container_engine_accelerators_tpu.serving.server import (
        _Batcher,
    )

    release = threading.Event()

    def slow_run(instances):
        release.wait(timeout=30)
        return [i * 2 for (i, ) in [(x,) for x in instances]]

    b = _Batcher(slow_run, max_batch=1, max_wait_ms=1, max_queue=2)
    try:
        first = b.submit_async(1)
        second = b.submit_async(2)
        assert first is not None and second is not None
        # The bound covers in-flight + queued rows: nothing else fits
        # until a row finishes, and admission is all-or-nothing (a
        # 2-row request cannot half-land).
        assert b.submit_async(3) is None
        assert b.submit_many([4, 5]) is None
        release.set()
        assert first.get(timeout=10) == ("ok", 2)
        assert second.get(timeout=10) == ("ok", 4)
        # Completion releases permits; admission works again.
        for _ in range(50):
            nxt = b.submit_async(6)
            if nxt is not None:
                break
            _time.sleep(0.1)
        assert nxt is not None
        assert nxt.get(timeout=10) == ("ok", 12)
    finally:
        release.set()
        b.stop()


def test_text_serving_ragged_batch():
    """Text rows of different lengths pad per row and trim per row —
    the raggedness every real text batch has."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )
    from container_engine_accelerators_tpu.serving.tokenizer import (
        ByteTokenizer,
    )

    model = TransformerLM(vocab_size=300, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm-rag", model, params, port=0,
                           max_new_tokens=8, max_batch=4,
                           tokenizer=ByteTokenizer())
    srv.start()
    try:
        out = post(srv, "/v1/models/lm-rag:generate",
                   {"text": ["hi", "hello"], "max_new_tokens": 3,
                    "logprobs": True})
        assert len(out["sequences"][0]) == 2 + 3
        assert len(out["sequences"][1]) == 5 + 3
        assert len(out["logprobs"][0]) == 5
        assert len(out["logprobs"][1]) == 8
        assert out["sequences"][0][:2] == [104, 105]
        assert len(out["completions"]) == 2
    finally:
        srv.stop()


def test_byte_tokenizer_out_of_range_marker():
    from container_engine_accelerators_tpu.serving.tokenizer import (
        ByteTokenizer,
    )

    tok = ByteTokenizer()
    assert tok.decode([104, 105, 290, 33]) == "hi�!"


def test_admission_budget_shared_across_variant_batchers():
    """The overload bound caps AGGREGATE admitted rows across all
    program-variant batchers of one server (ADVICE r2: a per-variant
    bound would scale with the number of variants clients exercise)."""
    import threading

    from container_engine_accelerators_tpu.serving.server import (
        SHED,
        _Admission,
        _Batcher,
    )

    release = threading.Event()

    def slow_run(instances):
        release.wait(timeout=30)
        return [0 for _ in instances]

    shared = _Admission(2)
    b1 = _Batcher(slow_run, max_batch=1, max_wait_ms=1,
                  admission=shared)
    b2 = _Batcher(slow_run, max_batch=1, max_wait_ms=1,
                  admission=shared)
    try:
        first = b1.submit_many([object()])
        assert first is not None           # 1 of 2 admitted
        assert b2.submit_many([object(), object()]) is None  # 1 free
        second = b2.submit_many([object()])
        assert second is not None          # 2 of 2 admitted
        assert b1.submit_many([object()]) is None  # aggregate full
        assert b1.submit(object()) == SHED  # shed sentinel, not error
        release.set()
        assert first[0].get(timeout=10)[0] == "ok"
        assert second[0].get(timeout=10)[0] == "ok"
    finally:
        release.set()
        b1.stop()
        b2.stop()


def test_windowed_server_constructs_engine_service():
    """ONE decode path: a sliding-window model builds the engine
    service like every other config (the per-row band mask gives
    each row its own window horizon) — the legacy run-to-completion
    batcher route is gone, and the engine service shares the
    server's one admission budget by construction."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          attention_window=8, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=2, buckets=[8])
    try:
        assert srv._engine_service is not None
        assert srv._engine_service._admission is srv._admission
        # The per-variant batcher surface no longer exists on the
        # server at all — nothing left to route around the engine.
        assert not hasattr(srv, "_batcher_for")
    finally:
        # Never started: stop() must not deadlock in
        # ThreadingHTTPServer.shutdown() (regression: it used to wait
        # forever for a serve loop that was never running).
        srv.stop()


def test_generate_speculative_greedy_path():
    """With a draft configured, plain-greedy requests draft/verify
    INSIDE the engine and return EXACTLY what the plain engine
    returns; sampled and penalized rows take the single-token lane
    of the SAME step program, so their traffic moves no speculation
    counters."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    draft = TransformerLM(vocab_size=64, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=48,
                          dtype=jnp.float32)
    dparams = draft.init(jax.random.PRNGKey(2),
                         jnp.zeros((1, 8), jnp.int32))["params"]

    def make(**kw):
        return GenerationServer("lm", model, params, port=0,
                                max_new_tokens=8, max_batch=2,
                                buckets=[8], **kw)

    plain = make()
    spec = make(draft_model=draft, draft_params=dparams,
                speculative_k=4)
    plain.start()
    spec.start()
    try:
        for payload in (
                {"prompts": [[1, 2, 3]], "max_new_tokens": 6},
                {"prompts": [[1, 2, 3]], "max_new_tokens": 6,
                 "eos_id": 7},
                {"prompts": [[4, 5, 6, 7, 8]], "max_new_tokens": 8},
        ):
            a = post(plain, "/v1/models/lm:generate", payload)
            b = post(spec, "/v1/models/lm:generate", payload)
            assert a["sequences"] == b["sequences"], payload
        import urllib.request as _u
        with _u.urlopen(f"http://localhost:{spec.port}/stats",
                        timeout=10) as resp:
            stats = json.loads(resp.read())
        # The greedy traffic drafted: the engine proposed chunks and
        # mirrored one draft prefill per admission.
        assert stats["spec_steps"] >= 3, stats
        assert stats["spec_proposed_tokens"] > 0, stats
        assert stats["draft_prefills"] >= 3, stats
        # Sampling and the repetition penalty are NOT
        # speculation-eligible (a sampled row's verify column would
        # need per-proposal acceptance sampling; a penalized draft
        # stream would need the target's seen state): those rows run
        # single-token in the SAME step program, so their traffic
        # must leave every speculation counter exactly where it was.
        for payload in (
                {"prompts": [[1, 2, 3]], "max_new_tokens": 4,
                 "temperature": 0.9},
                {"prompts": [[1, 2, 3]], "max_new_tokens": 4,
                 "temperature": 0.9, "top_p": 0.8},
                {"prompts": [[1, 2, 3]], "max_new_tokens": 4,
                 "repetition_penalty": 1.3},
        ):
            out = post(spec, "/v1/models/lm:generate", payload)
            assert len(out["sequences"][0]) == 7
        with _u.urlopen(f"http://localhost:{spec.port}/stats",
                        timeout=10) as resp:
            stats2 = json.loads(resp.read())
        for key in ("spec_proposed_tokens", "spec_accepted_tokens",
                    "draft_prefills"):
            assert stats2[key] == stats[key], (key, stats2)
    finally:
        plain.stop()
        spec.stop()


def test_generate_speculative_warm_covers_every_knob():
    """Warm-up on a speculative server compiles the COMPLETE program
    set before /healthz reports ready: sampled and penalized rows
    run single-token in the SAME widened step program the warm
    greedy rows built, so post-ready traffic with any knob triggers
    ZERO new compiles — measured directly on the engine's program
    caches. Warm rows themselves are synthetic: reset_counters drops
    them, so /stats opens with a zeroed speculation surface."""
    from container_engine_accelerators_tpu.analysis import retrace
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    draft = TransformerLM(vocab_size=64, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=48,
                          dtype=jnp.float32)
    dparams = draft.init(jax.random.PRNGKey(2),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=2,
                           buckets=[8, 16], warm=True,
                           draft_model=draft, draft_params=dparams,
                           speculative_k=4)
    srv.start()
    try:
        import urllib.request as _u
        with _u.urlopen(f"http://localhost:{srv.port}/stats",
                        timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["spec_steps"] == 0, stats
        assert stats["draft_prefills"] == 0, stats
        assert stats["speculative_acceptance_rate"] is None, stats
        paged = srv._engine_service._engine.paged
        programs = (retrace.engine_programs(paged)
                    + retrace.spec_engine_programs(paged))
        sizes = {name: fn._cache_size() for name, fn in programs}
        for payload in (
                {"prompts": [[1, 2, 3]], "max_new_tokens": 4,
                 "repetition_penalty": 1.3},
                {"prompts": [[1, 2, 3]], "max_new_tokens": 4,
                 "temperature": 0.9, "top_k": 8},
                {"prompts": [[1, 2, 3]], "max_new_tokens": 4},
        ):
            out = post(srv, "/v1/models/lm:generate", payload)
            assert len(out["sequences"][0]) == 7
        after = {name: fn._cache_size() for name, fn in programs}
        assert after == sizes, (sizes, after)
        # ... and the greedy request above did draft (same program
        # set, gate on): the counters move only for real traffic.
        with _u.urlopen(f"http://localhost:{srv.port}/stats",
                        timeout=10) as resp:
            stats2 = json.loads(resp.read())
        assert stats2["spec_proposed_tokens"] > 0, stats2
        assert stats2["draft_prefills"] == 1, stats2
    finally:
        srv.stop()


def test_generate_speculative_tight_headroom_gates_per_row():
    """A config with ZERO verify slack beyond the decode horizon
    (max_seq_len == bucket + max_new) used to force a whole-server
    plain fallback; the engine instead gates speculation PER ROW —
    a row drafts while pos + k fits its span and finishes
    single-token in the same program — so tight-headroom servers
    keep the speedup and stay token-identical to decode()."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.models.decode import decode
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    # max_seq_len 16 = bucket 8 + max_new 8: no slack for k anywhere
    # but inside each row's own unconsumed span.
    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=16,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=2, buckets=[8],
                           draft_model=model, draft_params=params,
                           speculative_k=4)
    srv.start()
    try:
        # Full-horizon request: drafts early, must flip to the
        # single-token lane when pos + k overruns the 16-token span.
        prompt = [4, 5, 6, 7, 8, 9, 10, 11]
        out = post(srv, "/v1/models/lm:generate",
                   {"prompts": [prompt], "max_new_tokens": 8})
        want = decode(model, params,
                      jnp.asarray([prompt], jnp.int32), 8)
        assert out["sequences"][0] == np.asarray(want)[0].tolist()
        import urllib.request as _u
        with _u.urlopen(f"http://localhost:{srv.port}/stats",
                        timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["spec_steps"] >= 1, stats
        assert stats["spec_proposed_tokens"] > 0, stats
        assert (stats["spec_accepted_tokens"]
                <= stats["spec_proposed_tokens"]), stats
    finally:
        srv.stop()


def test_generate_speculative_serves_logprobs():
    """Greedy logprobs requests still draft (the verify logits score
    committed tokens for free) and return exactly what the plain
    engine returns — same tokens, logprobs to float tolerance."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    draft = TransformerLM(vocab_size=64, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=48,
                          dtype=jnp.float32)
    dparams = draft.init(jax.random.PRNGKey(2),
                         jnp.zeros((1, 8), jnp.int32))["params"]

    def make(**kw):
        return GenerationServer("lm", model, params, port=0,
                                max_new_tokens=8, max_batch=2,
                                buckets=[8], **kw)

    plain = make()
    spec = make(draft_model=draft, draft_params=dparams,
                speculative_k=4)
    plain.start()
    spec.start()
    try:
        payload = {"prompts": [[1, 2, 3]], "max_new_tokens": 6,
                   "logprobs": True}
        a = post(plain, "/v1/models/lm:generate", payload)
        b = post(spec, "/v1/models/lm:generate", payload)
        assert a["sequences"] == b["sequences"]
        np.testing.assert_allclose(a["logprobs"], b["logprobs"],
                                   atol=1e-4)
        import urllib.request as _u
        with _u.urlopen(f"http://localhost:{spec.port}/stats",
                        timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["spec_steps"] >= 1, stats
        assert stats["spec_proposed_tokens"] > 0, stats
    finally:
        plain.stop()
        spec.stop()


def test_generate_speculative_filtered_topk1_is_greedy():
    """Filtered sampling on a speculative server takes the
    single-token lane of the SAME step program: with top_k=1 the
    filtered distribution is a point mass, so it must reproduce the
    drafted greedy output exactly — an end-to-end proof the sampling
    lane stayed exact while greedy rows were drafting next to it."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    draft = TransformerLM(vocab_size=64, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=48,
                          dtype=jnp.float32)
    dparams = draft.init(jax.random.PRNGKey(2),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=2,
                           buckets=[8], draft_model=draft,
                           draft_params=dparams, speculative_k=4)
    srv.start()
    try:
        greedy = post(srv, "/v1/models/lm:generate",
                      {"prompts": [[1, 2, 3]], "max_new_tokens": 6})
        import urllib.request as _u
        with _u.urlopen(f"http://localhost:{srv.port}/stats",
                        timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["spec_proposed_tokens"] > 0, stats
        topk1 = post(srv, "/v1/models/lm:generate",
                     {"prompts": [[1, 2, 3]], "max_new_tokens": 6,
                      "temperature": 1.0, "top_k": 1})
        assert greedy["sequences"] == topk1["sequences"]
        # The point-mass row sampled, so it neither drafted nor
        # mirrored a draft prefill.
        with _u.urlopen(f"http://localhost:{srv.port}/stats",
                        timeout=10) as resp:
            stats2 = json.loads(resp.read())
        assert (stats2["spec_proposed_tokens"]
                == stats["spec_proposed_tokens"]), stats2
        assert stats2["draft_prefills"] == stats["draft_prefills"]
    finally:
        srv.stop()


@pytest.fixture(scope="module")
def prefix_server():
    """System-prompt serving: a shared 6-token prefix prefilled once
    at construction; clients send suffixes only."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=40,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    prefix = [9, 8, 7, 6, 5, 4]
    srv = GenerationServer("lm-sys", model, params, port=0,
                           max_new_tokens=8, max_batch=4,
                           prefix_tokens=prefix, warm=True)
    srv.start()
    yield srv, model, params, prefix
    srv.stop()


def test_prefix_server_matches_full_decode(prefix_server):
    """A prefix-serving response is token-for-token the full decode
    of (prefix + suffix) — HTTP round trip included."""
    from container_engine_accelerators_tpu.models.decode import decode

    srv, model, params, prefix = prefix_server
    suffix = [1, 2, 3]
    out = post(srv, "/v1/models/lm-sys:generate",
               {"prompts": [suffix], "max_new_tokens": 6})
    seqs = out["sequences"]
    assert len(seqs) == 1 and len(seqs[0]) == len(suffix) + 6
    full = decode(
        model, params,
        jnp.asarray([prefix + suffix], jnp.int32), 6)
    want = np.asarray(full)[0, len(prefix):len(prefix) + len(suffix) + 6]
    assert seqs[0] == want.tolist()


def test_prefix_server_metadata_and_stats(prefix_server):
    srv, _, _, prefix = prefix_server
    meta = json.loads(urllib.request.urlopen(
        f"http://localhost:{srv.port}/v1/models/lm-sys",
        timeout=10).read())
    status = meta["model_version_status"][0]
    assert status["metadata"]["prefix_len"] == len(prefix)


def test_prefix_server_rejects_penalty_and_logprobs(prefix_server):
    srv, _, _, _ = prefix_server
    with pytest.raises(urllib.error.HTTPError) as err:
        post(srv, "/v1/models/lm-sys:generate",
             {"prompts": [[1, 2]], "max_new_tokens": 2,
              "repetition_penalty": 1.3})
    assert err.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as err:
        post(srv, "/v1/models/lm-sys:generate",
             {"prompts": [[1, 2]], "max_new_tokens": 2,
              "logprobs": True})
    assert err.value.code == 400


def test_prefix_server_sampling_filters_ride(prefix_server):
    """Sampling with top_k/top_p through the prefix path stays
    in-vocab and in the right response shape."""
    srv, model, _, _ = prefix_server
    out = post(srv, "/v1/models/lm-sys:generate",
               {"prompts": [[1, 2], [3, 4]], "max_new_tokens": 4,
                "temperature": 0.8, "top_k": 8, "top_p": 0.9})
    assert len(out["sequences"]) == 2
    for s in out["sequences"]:
        assert len(s) == 6
        assert all(0 <= t < model.vocab_size for t in s)


def test_prefix_server_construction_errors():
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=40,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    # A sliding-window TARGET composes with prefix serving and
    # speculation (the engine's per-row band mask handles it), but a
    # sliding-window DRAFT has no dense cache for the k-1 micro-step
    # scan: the engine refuses at construction, and the server
    # surfaces that refusal instead of building an unservable
    # replica.
    wmodel = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                           num_heads=4, max_seq_len=40,
                           attention_window=8, dtype=jnp.float32)
    wparams = wmodel.init(jax.random.PRNGKey(1),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="dense cache"):
        GenerationServer("x", wmodel, wparams, port=0,
                         max_new_tokens=8, prefix_tokens=[1, 2],
                         speculative_k=2, draft_model=wmodel,
                         draft_params=wparams)
    with pytest.raises(ValueError, match="0..63"):
        GenerationServer("x", model, params, port=0,
                         prefix_tokens=[1, 99])
    with pytest.raises(ValueError, match="warm_filters"):
        GenerationServer("x", model, params, port=0,
                         prefix_tokens=[1, 2],
                         warm_filters=[{"repetition_penalty": 1.2}])
    # Prefix eats max_seq_len: 40 - 8 new - 31 prefix = 1 <-- ok,
    # but 32-token prefix leaves none.
    with pytest.raises(ValueError, match="no room"):
        GenerationServer("x", model, params, port=0,
                         max_new_tokens=8,
                         prefix_tokens=list(range(32)))


def _post_stream(server, path, payload):
    """POST and read the ndjson stream; returns the parsed lines."""
    req = urllib.request.Request(
        f"http://localhost:{server.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    lines = []
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert resp.headers["Content-Type"] == "application/x-ndjson"
        for raw in resp:
            raw = raw.strip()
            if raw:
                lines.append(json.loads(raw))
    return lines


def test_stream_generate_matches_non_stream(lm_server):
    """"stream": true yields the same greedy tokens as the one-shot
    response, in >= 1 ndjson blocks, ending with {"done": true}."""
    one = post(lm_server, "/v1/models/lm:generate",
               {"prompts": [[1, 2, 3, 4]], "max_new_tokens": 8})
    lines = _post_stream(lm_server, "/v1/models/lm:generate",
                         {"prompts": [[1, 2, 3, 4]],
                          "max_new_tokens": 8, "stream": True})
    assert lines[-1] == {"done": True}
    got = [t for line in lines[:-1] for t in line["tokens"]]
    assert got == one["sequences"][0][4:]


def test_stream_generate_eos_ends_stream(lm_server):
    one = post(lm_server, "/v1/models/lm:generate",
               {"prompts": [[5, 6, 7]], "max_new_tokens": 8})
    eos = one["sequences"][0][3]  # first generated token
    lines = _post_stream(lm_server, "/v1/models/lm:generate",
                         {"prompts": [[5, 6, 7]],
                          "max_new_tokens": 8, "stream": True,
                          "eos_id": eos})
    toks = [t for line in lines[:-1] for t in line.get("tokens", [])]
    assert toks[-1] == eos and len(toks) <= 8
    assert lines[-1] == {"done": True}


def test_stream_validation(lm_server):
    for bad in ({"logprobs": True}, {"repetition_penalty": 1.2},
                {"prompts": [[1], [2]]}):
        body = {"prompts": [[1, 2]], "max_new_tokens": 4,
                "stream": True}
        body.update(bad)
        with pytest.raises(urllib.error.HTTPError) as err:
            post(lm_server, "/v1/models/lm:generate", body)
        assert err.value.code == 400


def test_stream_on_prefix_server_matches_plain(prefix_server):
    """Streaming on a system-prompt server continues the shared
    prefix state: tokens equal the non-streamed suffix response."""
    srv, _, _, _ = prefix_server
    one = post(srv, "/v1/models/lm-sys:generate",
               {"prompts": [[2, 4, 6]], "max_new_tokens": 6})
    lines = _post_stream(srv, "/v1/models/lm-sys:generate",
                         {"prompts": [[2, 4, 6]],
                          "max_new_tokens": 6, "stream": True})
    got = [t for line in lines[:-1] for t in line["tokens"]]
    assert got == one["sequences"][0][3:]


def test_stream_admission_released_without_iteration():
    """A streaming body that is closed without ever being iterated
    (client gone before the first write) must still release its
    admission slot — generator finalization alone would leak it."""
    from container_engine_accelerators_tpu.serving.server import (
        _StreamBody,
    )

    released = []

    def gen():
        try:
            yield {"tokens": [1]}
        finally:
            released.append("gen-finally")

    body = _StreamBody(gen(), lambda: released.append("slot"))
    body.close()  # never iterated
    assert released == ["slot"]  # slot freed; gen finally never ran
    # Iterated bodies release exactly once too.
    released.clear()
    body2 = _StreamBody(gen(), lambda: released.append("slot"))
    next(body2)
    body2.close()
    assert released == ["gen-finally", "slot"]
    body2.close()
    assert released == ["gen-finally", "slot"]  # idempotent


def test_stream_largest_bucket_fits_budget(prefix_server):
    """Streaming a prompt in the LARGEST bucket must fit the prefix
    state's capacity (regression: chunk-quantized cache sizing used
    to overflow max_total_len for big buckets and error mid-stream)."""
    srv, _, _, _ = prefix_server
    prompt = list(range(1, 21))  # 20 tokens -> top bucket
    one = post(srv, "/v1/models/lm-sys:generate",
               {"prompts": [prompt], "max_new_tokens": 8})
    lines = _post_stream(srv, "/v1/models/lm-sys:generate",
                         {"prompts": [prompt], "max_new_tokens": 8,
                          "stream": True})
    assert lines[-1] == {"done": True}
    assert not any("error" in l for l in lines)
    got = [t for line in lines[:-1] for t in line["tokens"]]
    assert got == one["sequences"][0][20:]


def test_stream_rides_warmed_engine_programs():
    """Engine streams need NO extra compiled programs: a stream is an
    ordinary slot whose tokens are forwarded per step, so after warm
    (prefill programs + insert/step) a streaming request — eos-
    bearing included — runs without growing the program set
    (engine_prefills counts one admission, and the stream arrives
    one token per line)."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer(
        "lm-ws", model, params, port=0, max_new_tokens=24,
        max_batch=2, warm=True,
        warm_filters=[{"stream": True, "temperature": 0},
                      {"stream": True}])  # accepted, inert in engine
    srv.start()
    try:
        assert srv.stats()["engine_prefills"] == 0  # reset post-warm
        lines = _post_stream(srv, "/v1/models/lm-ws:generate",
                             {"prompts": [[1, 2, 3]],
                              "max_new_tokens": 6, "stream": True,
                              "eos_id": 63})
        assert lines[-1] == {"done": True}
        got = [t for line in lines[:-1] for t in line["tokens"]]
        assert 1 <= len(got) <= 6
        assert all(len(line["tokens"]) == 1 for line in lines[:-1])
        assert srv.stats()["engine_prefills"] == 1
    finally:
        srv.stop()


def test_stream_close_mid_stream_releases_slot():
    """_StreamBody.close() mid-stream cancels the engine work: the
    slot retires at the next step boundary with no leak —
    slots_free returns to max, the admission permit frees, and the
    pool keeps serving."""
    import time

    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm-sc", model, params, port=0,
                           max_new_tokens=48, max_batch=2,
                           buckets=[8])
    srv.start()
    try:
        req = urllib.request.Request(
            f"http://localhost:{srv.port}/v1/models/lm-sc:generate",
            data=json.dumps({"prompts": [[1, 2, 3]],
                             "max_new_tokens": 48,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=30)
        # Read a couple of lines mid-stream, then abandon the
        # connection with most of the horizon unserved.
        for _ in range(2):
            resp.readline()
        resp.close()
        deadline = time.monotonic() + 30
        while True:
            stats = srv.stats()
            if (stats["slots_free"] == 2 and stats["slots_active"] == 0
                    and stats["queue_depth"] == 0):
                break
            assert time.monotonic() < deadline, stats
            time.sleep(0.1)
        # The freed slot (and admission permit) serve the next
        # request.
        out = post(srv, "/v1/models/lm-sc:generate",
                   {"prompts": [[4, 5]], "max_new_tokens": 4})
        assert len(out["sequences"][0]) == 6
    finally:
        srv.stop()


def test_engine_eos_recycles_slot_under_load():
    """A 1-slot pool with a queued request behind an EOS-terminating
    stream: the first request's early retirement hands its slot to
    the queued one without waiting out the horizon — steps stay far
    under two full budgets (run-to-completion cost)."""
    import threading

    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm-re", model, params, port=0,
                           max_new_tokens=32, max_batch=1,
                           buckets=[8])
    srv.start()
    try:
        # Discover A's second generated token and use it as A's EOS:
        # A then retires after 2 of its 32-token budget.
        probe = post(srv, "/v1/models/lm-re:generate",
                     {"prompts": [[1, 2, 3]], "max_new_tokens": 2})
        eos = probe["sequences"][0][4]
        base = srv.stats()["engine_steps"]
        results = {}

        def fire(tag, payload):
            results[tag] = post(srv, "/v1/models/lm-re:generate",
                                payload)

        t_a = threading.Thread(target=fire, args=(
            "a", {"prompts": [[1, 2, 3]], "max_new_tokens": 32,
                  "eos_id": eos}))
        t_b = threading.Thread(target=fire, args=(
            "b", {"prompts": [[4, 5, 6]], "max_new_tokens": 4}))
        t_a.start()
        t_a.join(timeout=0.0)  # let A hit the queue first
        t_b.start()
        t_a.join()
        t_b.join()
        seq_a = results["a"]["sequences"][0]
        assert eos in seq_a[3:]  # early EOS, padded to the horizon
        assert len(results["b"]["sequences"][0]) == 7
        steps = srv.stats()["engine_steps"] - base
        # Run-to-completion would cost ~31 + 3 steps; early retire +
        # recycle keeps it near 2 + 3 (slack for scheduling skew).
        assert steps <= 15, steps
    finally:
        srv.stop()


def test_stream_on_spec_server_matches_plain_greedy():
    """"stream": true on a speculative server rides the SAME engine
    rows: a verify step commits 1..k tokens, so stream chunks may
    carry several tokens at once, and the concatenated stream is
    exactly the non-stream greedy sequence."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    draft = TransformerLM(vocab_size=64, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=48,
                          dtype=jnp.float32)
    dparams = draft.init(jax.random.PRNGKey(2),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm-ss", model, params, port=0,
                           max_new_tokens=8, max_batch=2,
                           buckets=[8], draft_model=draft,
                           draft_params=dparams, speculative_k=4)
    srv.start()
    try:
        one = post(srv, "/v1/models/lm-ss:generate",
                   {"prompts": [[1, 2, 3]], "max_new_tokens": 6})
        lines = _post_stream(srv, "/v1/models/lm-ss:generate",
                             {"prompts": [[1, 2, 3]],
                              "max_new_tokens": 6, "stream": True})
        got = [t for line in lines[:-1] for t in line["tokens"]]
        assert got == one["sequences"][0][3:]
        assert lines[-1] == {"done": True}
        import urllib.request as _u
        with _u.urlopen(f"http://localhost:{srv.port}/stats",
                        timeout=10) as resp:
            stats = json.loads(resp.read())
        # Both requests (stream and not) drafted.
        assert stats["draft_prefills"] == 2, stats
        assert stats["spec_proposed_tokens"] > 0, stats
    finally:
        srv.stop()


def test_generate_speculative_windowed_model_routes_spec():
    """Sliding-window TARGET + dense draft: the engine's per-row
    band mask verifies chunks under the window, so default-knob
    traffic drafts and the output equals the plain windowed
    server's exactly. A windowed DRAFT stays refused at
    construction (the k-1 micro-step scan needs a dense cache)."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          attention_window=8, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    draft = TransformerLM(vocab_size=64, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=48,
                          dtype=jnp.float32)
    dparams = draft.init(jax.random.PRNGKey(2),
                         jnp.zeros((1, 8), jnp.int32))["params"]

    def make(**kw):
        return GenerationServer("lm", model, params, port=0,
                                max_new_tokens=8, max_batch=2,
                                buckets=[8], **kw)

    with pytest.raises(ValueError, match="dense cache"):
        make(draft_model=model, draft_params=params, speculative_k=4)
    plain = make()
    spec = make(draft_model=draft, draft_params=dparams,
                speculative_k=4)
    plain.start()
    spec.start()
    try:
        for payload in (
                {"prompts": [[1, 2, 3]], "max_new_tokens": 8},
                {"prompts": [[1, 2, 3]], "max_new_tokens": 8,
                 "eos_id": 7},
                {"prompts": [[4, 5, 6, 7, 8, 9, 10, 11]],
                 "max_new_tokens": 8},
        ):
            a = post(plain, "/v1/models/lm:generate", payload)
            b = post(spec, "/v1/models/lm:generate", payload)
            assert a["sequences"] == b["sequences"], payload
        import urllib.request as _u
        with _u.urlopen(f"http://localhost:{spec.port}/stats",
                        timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["spec_steps"] >= 3, stats
        assert stats["spec_proposed_tokens"] > 0, stats
    finally:
        plain.stop()
        spec.stop()


def test_generate_speculative_acceptance_telemetry():
    """/stats exposes the draft acceptance rate — the break-even
    model's alpha — accumulated across speculative calls."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=48,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer(
        "lm", model, params, port=0, max_new_tokens=8, max_batch=2,
        buckets=[8], draft_model=model, draft_params=params,
        speculative_k=4, warm=True)
    srv.start()
    try:
        # Warm-up's synthetic rows DID gate spec steps (they compile
        # the draft/verify programs) but reset_counters drops them:
        # the surface reports TRAFFIC's alpha only.
        stats0 = srv.stats()
        assert stats0["spec_steps"] == 0, stats0
        assert stats0["speculative_acceptance_rate"] is None, stats0
        post(srv, "/v1/models/lm:generate",
             {"prompts": [[1, 2, 3]], "max_new_tokens": 8})
        stats = srv.stats()
        # Self-draft: proposals re-derive the target's own argmax,
        # so acceptance sits at/near 1.0 — "near" because the draft
        # proposes through single-token micro-steps while verify
        # scores the same positions through a width-k chunk, and the
        # different reduction orders can flip argmax near-ties on a
        # random tiny model. The floor matches the spec-check gate.
        rate = stats["speculative_acceptance_rate"]
        assert rate is not None and 0.5 <= rate <= 1.0, stats
        assert (stats["spec_accepted_tokens"]
                <= stats["spec_proposed_tokens"]), stats
        # >= 1 by construction; > 1 iff any proposal landed — the
        # per-chip throughput multiplier the break-even model rates.
        assert stats["accepted_tokens_per_step"] > 1.0, stats
    finally:
        srv.stop()


def test_prefix_server_with_speculation_matches_plain_prefix():
    """prefix_tokens + speculative_k: default-knob traffic rides
    prefix speculation and returns EXACTLY what the prefix-only
    server returns; penalty traffic falls back to the plain prefix
    program; acceptance telemetry accumulates."""
    from container_engine_accelerators_tpu.models import TransformerLM
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model = TransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    prefix = [7, 11, 13, 17]

    def make(**kw):
        return GenerationServer("lm", model, params, port=0,
                                max_new_tokens=8, max_batch=2,
                                buckets=[8], prefix_tokens=prefix,
                                **kw)

    plain = make()
    spec = make(draft_model=model, draft_params=params,
                speculative_k=4)
    plain.start()
    spec.start()
    try:
        for payload in (
                {"prompts": [[1, 2, 3]], "max_new_tokens": 8},
                {"prompts": [[1, 2, 3]], "max_new_tokens": 8,
                 "eos_id": 9},
                {"prompts": [[4, 5, 6, 7, 8]], "max_new_tokens": 8,
                 "temperature": 0.0},
        ):
            a = post(plain, "/v1/models/lm:generate", payload)
            b = post(spec, "/v1/models/lm:generate", payload)
            assert a["sequences"] == b["sequences"], payload
        stats = spec.stats()
        assert stats["spec_steps"] >= 3, stats
        # Self-draft over the same prefix states: at/near-full
        # acceptance (width-k verify vs micro-step draft reduction
        # orders can flip argmax near-ties; floor = spec-check's).
        rate = stats["speculative_acceptance_rate"]
        assert rate is not None and rate >= 0.5, stats
        # Penalty requests still get the prefix-mode 400 (they need
        # prefix-token visibility) — the composition does not widen
        # the accepted request surface.
        with pytest.raises(urllib.error.HTTPError) as err:
            post(spec, "/v1/models/lm:generate",
                 {"prompts": [[1, 2, 3]], "max_new_tokens": 4,
                  "repetition_penalty": 1.3})
        assert err.value.code == 400
    finally:
        plain.stop()
        spec.stop()


def _get(server, path):
    """GET returning (status, headers, body-bytes); an HTTP error
    status is an answer here (the fleet collector's convention)."""
    try:
        with urllib.request.urlopen(
                f"http://localhost:{server.port}{path}",
                timeout=10) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), e.read()


def test_metrics_endpoint_exposes_serving_histograms(lm_server):
    from container_engine_accelerators_tpu.obs.fleet import (
        histograms_from_text,
    )
    from container_engine_accelerators_tpu.obs.metric_names import (
        SERVING_TTFT,
    )

    post(lm_server, "/v1/models/lm:generate",
         {"prompts": [[1, 2, 3]], "max_new_tokens": 4})
    status, headers, body = _get(lm_server, "/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    text = body.decode()
    # Bucket lines (not just summaries) — the fleet collector
    # de-cumulates these for the exact fleet-wide merge, so the
    # exposition must round-trip through the inverse parser.
    assert f"{SERVING_TTFT}_bucket{{" in text
    parsed = histograms_from_text(text, names={SERVING_TTFT})
    assert sum(h.count for h in parsed.values()) >= 1


def test_stats_carries_engine_identity(lm_server):
    status, _, body = _get(lm_server, "/stats")
    assert status == 200
    stats = json.loads(body)
    assert stats["engine_id"] == lm_server.engine_id()
    # role@host:port[pid]: the port distinguishes replicas that
    # share a host and the journal's process identity rides along.
    assert f":{lm_server.port}[" in stats["engine_id"]
    assert stats["identity"]["port"] == lm_server.port


def test_readyz_503_carries_structured_drain_body(lm_server):
    lm_server.begin_drain()
    try:
        status, headers, body = _get(lm_server, "/readyz")
        assert status == 503
        detail = json.loads(body)
        assert detail["state"] == "draining"
        assert detail["status"] == "draining"  # pre-fleet consumers
        assert isinstance(detail["retry_after_s"], (int, float))
        assert "saturation_cause" in detail
        assert float(headers["Retry-After"]) == pytest.approx(
            detail["retry_after_s"])
    finally:
        # The module-scoped fixture outlives this test: un-drain so
        # later tests can still POST.
        lm_server._draining = False
    ok_status, _, ok_body = _get(lm_server, "/readyz")
    assert ok_status == 200
    assert json.loads(ok_body)["status"] == "ready"
