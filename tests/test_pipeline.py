# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Pipeline-parallel tests on the 8-device CPU mesh.

The GPipe schedule is exact (microbatching changes nothing
numerically for per-example stages), so forward AND backward are
equality checks against folding the stages sequentially on one
device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.parallel import (
    build_pipeline_mesh,
    circular_pipeline_apply,
    circular_stage_order,
    pipeline_apply,
    stack_stage_params,
    stage_sharding,
)

# Tier-1 budget: this module compiles many distinct XLA programs and
# runs minutes on the CI CPU mesh. It only became collectable when the
# shard_map compat shim fixed the jax-version import error, and
# including it would blow the 870s tier-1 cap — so it runs in the full
# lane (`make test` / pytest without `-m "not slow"`) instead.
pytestmark = pytest.mark.slow


D = 8


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_params(stages, key):
    ks = jax.random.split(key, stages)
    return stack_stage_params([
        {"w": jax.random.normal(k, (D, D)) * 0.5,
         "b": jnp.zeros((D,))} for k in ks])


def sequential_apply(params, x):
    for i in range(jax.tree_util.tree_leaves(params)[0].shape[0]):
        x = stage_fn(jax.tree_util.tree_map(lambda w: w[i], params), x)
    return x


@pytest.mark.parametrize("stages,data,microbatches", [
    (4, 2, 4),   # dp x pp
    (8, 1, 4),   # pure pp, fewer microbatches than stages
    (2, 4, 8),   # shallow pipe, deep microbatching
    (2, 2, 8),   # round-1 flake suspect: dp=2 x pp=2, deep microbatching
])
def test_pipeline_matches_sequential(stages, data, microbatches):
    mesh = build_pipeline_mesh(stages, data=data)
    params = make_params(stages, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, D))
    want = sequential_apply(params, x)
    got = pipeline_apply(mesh, stage_fn, params, x,
                         num_microbatches=microbatches)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match_sequential():
    stages, microbatches = 4, 4
    mesh = build_pipeline_mesh(stages, data=2)
    params = make_params(stages, jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (8, D))

    def pipe_loss(params):
        return jnp.mean(pipeline_apply(
            mesh, stage_fn, params, x,
            num_microbatches=microbatches) ** 2)

    def seq_loss(params):
        return jnp.mean(sequential_apply(params, x) ** 2)

    got = jax.grad(pipe_loss)(params)
    want = jax.grad(seq_loss)(params)
    jax.tree_util.tree_map(
        lambda g, w: np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=1e-5, atol=1e-6),
        got, want)


def test_pipeline_train_step_jits():
    """Full jitted train step: loss + grads + SGD update with stage
    params sharded over the pipe axis, batch over data."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    stages, microbatches = 4, 2
    mesh = build_pipeline_mesh(stages, data=2)
    params = make_params(stages, jax.random.PRNGKey(4))
    shardings = stage_sharding(mesh, params)
    params = jax.device_put(params, shardings)
    b_shard = NamedSharding(mesh, P("data"))
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(5), (8, D)), b_shard)
    y = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(6), (8, D)), b_shard)

    @jax.jit
    def train_step(params, x, y):
        def loss_fn(params):
            out = pipeline_apply(mesh, stage_fn, params, x,
                                 num_microbatches=microbatches)
            return jnp.mean((out - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads)
        return params, loss

    params, loss0 = train_step(params, x, y)
    for _ in range(5):
        params, loss = train_step(params, x, y)
    assert float(loss) < float(loss0)  # it learns
    w = jax.tree_util.tree_leaves(params)[0]
    assert w.sharding.spec[0] == "pipe"  # stages stayed put


def test_microbatch_divisibility_error():
    mesh = build_pipeline_mesh(4, data=2)
    params = make_params(4, jax.random.PRNGKey(7))
    x = jnp.zeros((6, D))  # 3 per data shard, not divisible by 2
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(mesh, stage_fn, params, x, num_microbatches=2)


@pytest.mark.parametrize("stages,pipe,data,microbatches", [
    (8, 4, 2, 4),    # v=2, M == P: one injection group
    (8, 2, 4, 8),    # v=4, M = 4P: chained injection groups
    (2, 2, 4, 4),    # v=1: degenerates to the GPipe schedule
    (12, 4, 1, 5),   # v=3, M % P != 0: masked-tail injection group
])
def test_circular_pipeline_matches_sequential(stages, pipe, data,
                                              microbatches):
    mesh = build_pipeline_mesh(pipe, data=data)
    params = make_params(stages, jax.random.PRNGKey(8))
    x = jax.random.normal(jax.random.PRNGKey(9),
                          (data * microbatches * 2, D))
    want = sequential_apply(params, x)
    got = circular_pipeline_apply(mesh, stage_fn, params, x,
                                  num_microbatches=microbatches)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_circular_pipeline_grads_match_sequential():
    stages, pipe, microbatches = 8, 4, 4
    mesh = build_pipeline_mesh(pipe, data=2)
    params = make_params(stages, jax.random.PRNGKey(10))
    x = jax.random.normal(jax.random.PRNGKey(11), (8, D))

    def pipe_loss(params):
        return jnp.mean(circular_pipeline_apply(
            mesh, stage_fn, params, x,
            num_microbatches=microbatches) ** 2)

    def seq_loss(params):
        return jnp.mean(sequential_apply(params, x) ** 2)

    got = jax.grad(pipe_loss)(params)
    want = jax.grad(seq_loss)(params)
    jax.tree_util.tree_map(
        lambda g, w: np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=1e-5, atol=1e-6),
        got, want)


def test_circular_placement_order_matches_natural():
    """pre_permuted=True on a circular_stage_order-permuted stack is
    exactly the natural-order apply — the train-loop layout that
    keeps the per-step placement all-to-all out of the step."""
    stages, pipe, microbatches = 8, 4, 4
    mesh = build_pipeline_mesh(pipe, data=2)
    params = make_params(stages, jax.random.PRNGKey(16))
    x = jax.random.normal(jax.random.PRNGKey(17), (16, D))
    order = circular_stage_order(stages, pipe)
    placed = jax.tree_util.tree_map(lambda w: w[order], params)
    want = circular_pipeline_apply(mesh, stage_fn, params, x,
                                   num_microbatches=microbatches)
    got = circular_pipeline_apply(mesh, stage_fn, placed, x,
                                  num_microbatches=microbatches,
                                  pre_permuted=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-7)


def test_circular_pipeline_jitted_train_step():
    """Interleaved schedule inside a jitted SGD step with the stacked
    stages sharded over the pipe axis in PLACEMENT order (the layout
    that keeps the placement all-to-all out of the step; grads and
    updates stay in placement order, which is self-consistent)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    stages, pipe, microbatches = 8, 4, 2
    mesh = build_pipeline_mesh(pipe, data=2)
    params = make_params(stages, jax.random.PRNGKey(12))
    order = circular_stage_order(stages, pipe)
    params = jax.tree_util.tree_map(lambda w: w[order], params)
    params = jax.device_put(params, stage_sharding(mesh, params))
    b_shard = NamedSharding(mesh, P("data"))
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(13), (8, D)), b_shard)
    y = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(14), (8, D)), b_shard)

    @jax.jit
    def train_step(params, x, y):
        def loss_fn(params):
            out = circular_pipeline_apply(
                mesh, stage_fn, params, x,
                num_microbatches=microbatches, pre_permuted=True)
            return jnp.mean((out - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads)
        return params, loss

    params, loss0 = train_step(params, x, y)
    for _ in range(5):
        params, loss = train_step(params, x, y)
    assert float(loss) < float(loss0)


def test_circular_stage_count_error():
    mesh = build_pipeline_mesh(4, data=2)
    params = make_params(6, jax.random.PRNGKey(15))  # 6 % 4 != 0
    x = jnp.zeros((8, D))
    with pytest.raises(ValueError, match="multiple"):
        circular_pipeline_apply(mesh, stage_fn, params, x,
                                num_microbatches=2)


def test_pipelined_lm_matches_sequential_blocks():
    """PipelinedLM.apply (blocks as circular pipeline stages over a
    (data, pipe) mesh) equals folding the same blocks sequentially
    on one device — the real-model pipeline contract."""
    from container_engine_accelerators_tpu.parallel.pipeline_lm import (
        PipelinedLM,
    )

    lm = PipelinedLM(vocab_size=61, embed_dim=16, num_layers=8,
                     num_heads=4, max_seq_len=16, pipe=4,
                     dtype=jnp.float32)
    mesh = build_pipeline_mesh(4, data=2)
    params = lm.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 12), 0, 61)
    got = lm.apply(params, tokens, mesh=mesh, num_microbatches=2)
    want = lm.reference_apply(params, tokens)
    assert got.shape == (8, 12, 61)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_pipelined_lm_train_step_learns():
    """Jitted next-token train step over the pipelined LM: blocks
    sharded over the pipe axis, batch over data, loss decreases."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from container_engine_accelerators_tpu.parallel.pipeline_lm import (
        PipelinedLM,
    )

    lm = PipelinedLM(vocab_size=31, embed_dim=16, num_layers=4,
                     num_heads=4, max_seq_len=16, pipe=4,
                     dtype=jnp.float32)
    mesh = build_pipeline_mesh(4, data=2)
    params = lm.init(jax.random.PRNGKey(2))
    params = jax.device_put(params, lm.shardings(mesh, params))
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(3), (8, 12), 0, 31),
        NamedSharding(mesh, P("data")))

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(params):
            logits = lm.apply(params, tokens, mesh=mesh,
                              num_microbatches=2)
            logp = jax.nn.log_softmax(logits[:, :-1])
            tgt = tokens[:, 1:]
            return -jnp.mean(jnp.take_along_axis(
                logp, tgt[..., None], axis=-1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, loss0 = train_step(params, opt_state, tokens)
    for _ in range(8):
        params, opt_state, loss = train_step(params, opt_state,
                                             tokens)
    assert float(loss) < float(loss0)
    w = jax.tree_util.tree_leaves(params["blocks"])[0]
    assert w.sharding.spec[0] == "pipe"


def test_pipelined_lm_layer_divisibility_error():
    from container_engine_accelerators_tpu.parallel.pipeline_lm import (
        PipelinedLM,
    )

    with pytest.raises(ValueError, match="fold"):
        PipelinedLM(vocab_size=31, embed_dim=16, num_layers=6,
                    num_heads=4, max_seq_len=16, pipe=4)
    # A mesh whose pipe axis differs from the model's must be
    # refused loudly — it would silently run blocks out of order.
    lm = PipelinedLM(vocab_size=31, embed_dim=16, num_layers=8,
                     num_heads=4, max_seq_len=16, pipe=4,
                     dtype=jnp.float32)
    params = lm.init(jax.random.PRNGKey(0))
    mesh2 = build_pipeline_mesh(2, data=4)
    with pytest.raises(ValueError, match="placement order"):
        lm.apply(params, jnp.zeros((8, 8), jnp.int32), mesh=mesh2,
                 num_microbatches=2)


def test_pipelined_lm_remat_grads_match():
    """remat=True changes memory/recompute, never the math: loss and
    grads equal the non-remat model exactly."""
    from container_engine_accelerators_tpu.parallel.pipeline_lm import (
        PipelinedLM,
    )

    kw = dict(vocab_size=31, embed_dim=16, num_layers=8, num_heads=4,
              max_seq_len=16, pipe=4, dtype=jnp.float32)
    lm = PipelinedLM(**kw)
    lm_r = PipelinedLM(**kw, remat=True)
    mesh = build_pipeline_mesh(4, data=2)
    params = lm.init(jax.random.PRNGKey(20))
    tokens = jax.random.randint(jax.random.PRNGKey(21), (8, 12), 0, 31)

    def loss(model, params):
        logits = model.apply(params, tokens, mesh=mesh,
                             num_microbatches=2)
        logp = jax.nn.log_softmax(logits[:, :-1])
        return -jnp.mean(jnp.take_along_axis(
            logp, tokens[:, 1:, None], axis=-1))

    l0, g0 = jax.value_and_grad(lambda p: loss(lm, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(lm_r, p))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g0, g1)
