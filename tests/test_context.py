# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Context-parallel attention tests on the 8-device CPU mesh.

Both schedules are exact, so every test is an equality check against
dense single-device attention — the strongest property available.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.parallel import (
    build_context_mesh,
    dot_product_attention,
    ring_attention,
    ulysses_attention,
)
from container_engine_accelerators_tpu.parallel.context import CONTEXT_AXIS

B, S, H, D = 2, 32, 4, 8


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.fixture(scope="module", params=[2, 4, 8])
def mesh(request):
    return build_context_mesh(context=request.param)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(mesh, qkv, causal):
    q, k, v = qkv
    want = dot_product_attention(q, k, v, causal=causal)
    got = ring_attention(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_kv_subblocking_exact(monkeypatch, causal):
    """Force multiple within-hop K sub-blocks (the long-context
    memory path) and require exactness — fwd and bwd — vs dense."""
    from container_engine_accelerators_tpu.parallel import context as ctx

    monkeypatch.setattr(ctx, "_KV_BLOCK", 8)  # S/P = 32 -> 4 blocks
    mesh = build_context_mesh(context=2)
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q, k, v = (jax.random.normal(key, (1, 64, 2, 8), jnp.float32)
               for key in ks)
    want = dot_product_attention(q, k, v, causal=causal)
    got = ring_attention(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss(fn, *args):
        return jnp.sum(fn(*args) ** 2)

    g_want = jax.grad(lambda x: loss(
        dot_product_attention, x, k, v, causal))(q)
    g_got = jax.grad(lambda x: loss(
        lambda a, b, c, cz: ring_attention(mesh, a, b, c, causal=cz),
        x, k, v, causal))(q)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=2e-4, atol=2e-4)

    # Ulysses' local attention runs the same sub-blocked schedule.
    got_u = ulysses_attention(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got_u), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(qkv, causal):
    mesh = build_context_mesh(context=4)  # H=4 divides
    q, k, v = qkv
    want = dot_product_attention(q, k, v, causal=causal)
    got = ulysses_attention(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = build_context_mesh(context=8)  # H=4 does not divide
    q = k = v = jnp.zeros((B, S, H, D))
    with pytest.raises(ValueError, match="heads not divisible"):
        ulysses_attention(mesh, q, k, v)


def test_ring_gradients_match_dense(qkv):
    """The ring must be exact under differentiation too — it is the
    building block for long-context training, not just inference."""
    mesh = build_context_mesh(context=4)
    q, k, v = qkv

    def dense_loss(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(mesh, q, k, v, causal=True) ** 2)

    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_ring_under_jit_with_data_axis(qkv):
    """jit + 2x4 (data x context) mesh: the deployment shape, where
    batch shards over data and sequence over context."""
    mesh = build_context_mesh(context=4, data=2)
    q, k, v = qkv

    @jax.jit
    def f(q, k, v):
        return ring_attention(mesh, q, k, v, causal=True)

    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(want), rtol=2e-5, atol=2e-5)


def test_context_mesh_axes():
    mesh = build_context_mesh(context=4)
    assert mesh.shape[CONTEXT_AXIS] == 4
    assert mesh.shape["data"] == 2
    with pytest.raises(ValueError, match="do not factor"):
        build_context_mesh(context=3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_hops_match_dense(qkv, causal):
    """The Pallas-per-hop path (TPU default): each hop computes
    (o, lse) with the flash kernel, hops merge by logsumexp weighting.
    Must equal dense exactly — fwd and bwd — including hops that are
    fully causally masked (lse forced to -inf)."""
    mesh = build_context_mesh(context=4)
    q, k, v = qkv
    want = dot_product_attention(q, k, v, causal=causal)
    got = ring_attention(mesh, q, k, v, causal=causal, use_flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def dense_loss(t):
        return jnp.sum(dot_product_attention(
            t[0], t[1], t[2], causal=causal) ** 2)

    def flash_loss(t):
        return jnp.sum(ring_attention(
            mesh, t[0], t[1], t[2], causal=causal,
            use_flash=True) ** 2)

    want_g = jax.grad(dense_loss)((q, k, v))
    got_g = jax.grad(flash_loss)((q, k, v))
    for g, w in zip(got_g, want_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_dense(qkv, causal):
    mesh = build_context_mesh(context=4)
    q, k, v = qkv
    want = dot_product_attention(q, k, v, causal=causal)
    got = ulysses_attention(mesh, q, k, v, causal=causal,
                            use_flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_lse_matches_logsumexp():
    """flash_attention_lse's second output is the row logsumexp of
    the (scaled, masked) scores — the contract the ring merge relies
    on."""
    from container_engine_accelerators_tpu.ops import (
        flash_attention_lse,
    )

    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(key, (1, 40, 2, 8), jnp.float32)
               for key in ks)
    _, lse = flash_attention_lse(q, k, v, causal=True, block=128)
    scale = 1.0 / np.sqrt(8)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    qp = jax.lax.broadcasted_iota(jnp.int32, (40, 40), 0)
    kp = jax.lax.broadcasted_iota(jnp.int32, (40, 40), 1)
    s = jnp.where(qp >= kp, s, -1e9)
    want = jax.scipy.special.logsumexp(s, axis=-1).transpose(0, 2, 1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
