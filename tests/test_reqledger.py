# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Per-request attribution ledger + saturation math (obs.reqledger).

Pure host-clock unit tests (jax-free, like the module): the
sum-to-wall partition under a fake clock, the record ring bound, the
reset seam, and the saturation formula at the slots/blocks/queue
corners the serving loop publishes from.
"""

import json

import pytest

from container_engine_accelerators_tpu.obs import Tracer
from container_engine_accelerators_tpu.obs.reqledger import (
    ATTRIBUTION_BUCKETS,
    RequestLedger,
    RequestTimeline,
    saturation,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_timeline_laps_partition_wall_exactly():
    clk = FakeClock()
    tl = RequestTimeline(clock=clk)
    clk.t = 0.5
    tl.lap("queue_wait")
    clk.t = 2.5
    tl.lap("block_wait")
    clk.t = 2.6
    tl.lap("prefill")
    tl.note_first_token()
    clk.t = 3.0
    tl.lap("decode_gap")
    clk.t = 3.1
    rec = tl.finish("completed", tokens=4, prompt_len=6)
    assert rec["wall_s"] == pytest.approx(3.1)
    assert rec["buckets"]["queue_wait"] == pytest.approx(0.5)
    assert rec["buckets"]["block_wait"] == pytest.approx(2.0)
    assert rec["buckets"]["prefill"] == pytest.approx(0.1)
    assert rec["buckets"]["decode_gap"] == pytest.approx(0.4)
    assert rec["buckets"]["other"] == pytest.approx(0.1)  # residue
    # The serialized record honors the same invariant the floats do.
    assert sum(rec["buckets"].values()) == pytest.approx(
        rec["wall_s"], abs=1e-9)
    assert rec["ttft_s"] == pytest.approx(2.6)
    assert rec["outcome"] == "completed"
    assert rec["tokens"] == 4 and rec["prompt_len"] == 6
    assert set(rec["buckets"]) == set(ATTRIBUTION_BUCKETS)
    json.dumps(rec)  # JSON-safe by contract


def test_timeline_move_reattributes_and_clamps():
    clk = FakeClock()
    tl = RequestTimeline(clock=clk)
    clk.t = 1.0
    tl.lap("prefill")
    # The rehydrate seam: measured upload time moves out of prefill.
    assert tl.move("prefill", "rehydrate", 0.25) == pytest.approx(0.25)
    assert tl.buckets["prefill"] == pytest.approx(0.75)
    # Clamped: a mismeasured (too-large) move cannot break the
    # partition.
    assert tl.move("prefill", "rehydrate", 5.0) == pytest.approx(0.75)
    rec = tl.finish("completed", now=1.0)
    assert rec["buckets"]["rehydrate"] == pytest.approx(1.0)
    assert sum(rec["buckets"].values()) == pytest.approx(1.0)


def test_timeline_cancel_residue_lands_in_other():
    clk = FakeClock()
    tl = RequestTimeline(clock=clk)
    clk.t = 0.2
    tl.lap("prefill")
    tl.note_first_token()
    clk.t = 0.9  # cancel lands mid-stream, after the last token
    rec = tl.finish("cancelled", tokens=1, stream=True)
    assert rec["outcome"] == "cancelled" and rec["stream"]
    assert rec["buckets"]["other"] == pytest.approx(0.7)
    assert sum(rec["buckets"].values()) == pytest.approx(
        rec["wall_s"])


def _record(wall=1.0, **buckets):
    clk = FakeClock()
    tl = RequestTimeline(clock=clk)
    for bucket, dt in buckets.items():
        clk.t += dt
        tl.lap(bucket)
    clk.t = wall
    return tl.finish("completed", now=clk.t)


def test_ledger_ring_bound_and_totals():
    led = RequestLedger(capacity=4, tracer=Tracer(enabled=False))
    for i in range(7):
        led.add(_record(wall=1.0 + i, queue_wait=0.5))
    assert led.retired_total() == 7
    records = led.records()
    assert len(records) == 4  # the ring bound
    # Newest first: the most recent wall is 7.0.
    assert records[0]["wall_s"] == pytest.approx(7.0)
    assert records[-1]["wall_s"] == pytest.approx(4.0)
    assert len(led.records(limit=2)) == 2
    state = led.state(max_rows=3)
    assert state["capacity"] == 4
    assert state["retired_total"] == 7
    assert len(state["records"]) == 3


def test_ledger_attribution_stats_and_reset():
    led = RequestLedger(capacity=8, tracer=Tracer(enabled=False))
    led.add(_record(wall=1.0, block_wait=0.8, prefill=0.1))
    stats = led.attribution_stats()
    assert set(stats) == set(ATTRIBUTION_BUCKETS)
    assert stats["block_wait"]["count"] == 1
    assert stats["block_wait"]["total_s"] == pytest.approx(0.8)
    assert stats["block_wait"]["p99_ms"] is not None
    # The reset seam (reset_counters rides it): ring, totals, and
    # histograms all zero IN PLACE.
    led.reset()
    assert led.retired_total() == 0
    assert led.records() == []
    stats = led.attribution_stats()
    assert all(s["count"] == 0 and s["p99_ms"] is None
               for s in stats.values())


def test_saturation_slots_corners():
    empty = saturation(slots_active=0, slots_total=8,
                       queue_horizon_s=1.0)
    assert empty["causes"]["slots"] == 0.0
    assert empty["max"] == 0.0
    full = saturation(slots_active=8, slots_total=8,
                      queue_horizon_s=1.0)
    assert full["causes"]["slots"] == 1.0
    assert full["max"] == 1.0
    # Dense pool: no kv_blocks cause at all (absent, not 0 — a
    # router must not read "not applicable" as "healthy samples").
    assert "kv_blocks" not in empty["causes"]


def test_saturation_block_corners_dominate_max():
    # Block-starved at low slot occupancy: max-over-causes must
    # surface the starvation an average would hide.
    sat = saturation(slots_active=2, slots_total=16,
                     blocks_available=0, blocks_usable=40,
                     queue_horizon_s=1.0)
    assert sat["causes"]["kv_blocks"] == 1.0
    assert sat["causes"]["slots"] == pytest.approx(0.125)
    assert sat["max"] == 1.0
    idle = saturation(slots_active=0, slots_total=16,
                      blocks_available=40, blocks_usable=40,
                      queue_horizon_s=1.0)
    assert idle["causes"]["kv_blocks"] == 0.0


def test_saturation_queue_age_corners():
    sat = saturation(slots_active=0, slots_total=1,
                     oldest_wait_s=0.5, queue_horizon_s=1.0)
    assert sat["causes"]["queue_age"] == pytest.approx(0.5)
    # Clamped at the horizon; disarmed (<= 0 horizon) reads 0.
    over = saturation(slots_active=0, slots_total=1,
                      oldest_wait_s=9.0, queue_horizon_s=1.0)
    assert over["causes"]["queue_age"] == 1.0
    off = saturation(slots_active=0, slots_total=1,
                     oldest_wait_s=9.0, queue_horizon_s=0.0)
    assert off["causes"]["queue_age"] == 0.0
    # Empty queue: 0 whatever the horizon.
    none = saturation(slots_active=0, slots_total=1,
                      oldest_wait_s=None, queue_horizon_s=1.0)
    assert none["causes"]["queue_age"] == 0.0
