# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Fault-injection e2e through the REAL entry binary.

The last seam between layers 6 (health), 4 (manager) and 5 (gRPC
adapters): everything below runs `cmd/tpu_device_plugin.py` as a
subprocess — the exact binary the DaemonSet ships — against a fake
node (device files + state dir + kubelet Registration stub), then
drives the demo/tpu-error fault contract end-to-end:

    inject (state file write, what inject_fault.c does)
      -> health poller picks it up
      -> ListAndWatch pushes Unhealthy
      -> Allocate of the sick chip is refused
      -> recovery (state file cleared)
      -> ListAndWatch pushes Healthy
      -> Allocate succeeds again.

Reference analog: demo/gpu-error exercising Xid -> unhealthy in a
live cluster (VERDICT r4 item 7); here the whole loop runs
hardware-free, the way the reference's own plugin tests fake
/dev and the kubelet.
"""

import os
import signal
import subprocess
import sys
import time

import grpc
import pytest

from container_engine_accelerators_tpu.plugin import api
from tests.conftest import REPO_ROOT
from tests.plugin_helpers import KubeletStub, short_tmpdir

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"


def _wait_for(predicate, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(interval)
    return None


def _plugin_socket(plugin_dir):
    socks = [f for f in os.listdir(plugin_dir)
             if f.startswith("tpu-") and f.endswith(".sock")]
    return (os.path.join(plugin_dir, socks[0])
            if len(socks) == 1 else None)


def _health_by_id(response):
    return {d.ID: d.health for d in response.devices}


@pytest.fixture
def entry_node():
    """A fake node + the entry binary running against it."""
    root = short_tmpdir()
    dev = os.path.join(root, "dev")
    state = os.path.join(root, "state")
    plugin_dir = os.path.join(root, "plugin")
    os.mkdir(dev)
    os.mkdir(state)
    os.mkdir(plugin_dir)
    for i in range(2):
        open(os.path.join(dev, f"accel{i}"), "w").close()
        os.mkdir(os.path.join(state, f"accel{i}"))

    kubelet = KubeletStub(os.path.join(plugin_dir, "kubelet.sock"))
    kubelet.start()

    env = dict(os.environ, CEA_CHIP_BACKEND="python")
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(REPO_ROOT, "cmd", "tpu_device_plugin.py"),
         "--device-dir", dev, "--state-dir", state,
         "--plugin-directory", plugin_dir,
         "--host-path", os.path.join(root, "no-libtpu"),
         "--config-file", os.path.join(root, "no-config.json"),
         "--enable-health-monitoring",
         "--health-poll-interval", "0.1"],
        env=env, stderr=subprocess.PIPE)
    try:
        assert _wait_for(lambda: _plugin_socket(plugin_dir)), \
            proc.stderr.read().decode() if proc.poll() is not None \
            else "plugin socket never appeared"
        assert kubelet.event.wait(10), "plugin never registered"
        yield {"state": state, "plugin_dir": plugin_dir,
               "proc": proc}
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        kubelet.stop()


def test_inject_poll_listandwatch_allocate_recover(entry_node):
    state = entry_node["state"]
    sock = _plugin_socket(entry_node["plugin_dir"])
    health_file = os.path.join(state, "accel0", "health")

    with grpc.insecure_channel(f"unix://{sock}") as channel:
        stub = api.DevicePluginV1Beta1Stub(channel)
        stream = stub.ListAndWatch(api.v1beta1_pb2.Empty(),
                                   timeout=120)

        first = _health_by_id(next(stream))
        assert first == {"accel0": HEALTHY, "accel1": HEALTHY}

        request = api.v1beta1_pb2.AllocateRequest(container_requests=[
            api.v1beta1_pb2.ContainerAllocateRequest(
                devicesIDs=["accel0"])])
        response = stub.Allocate(request, timeout=10)
        assert response.container_responses[0].envs

        # Inject the fault exactly as demo/tpu-error/inject_fault.c
        # does: a fatal token in the node-published state file the
        # health poller reads.
        with open(health_file, "w") as f:
            f.write("uncorrectable_ecc")

        got = _wait_for_stream_health(
            stream, {"accel0": UNHEALTHY, "accel1": HEALTHY})
        assert got, "ListAndWatch never reported the injected fault"

        # The scheduling gate: allocating the sick chip is refused
        # with INVALID_ARGUMENT (manager.py maps the health check
        # the way the reference refuses unhealthy GPUs).
        with pytest.raises(grpc.RpcError) as err:
            stub.Allocate(request, timeout=10)
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT

        # The healthy sibling still allocates — the fault is scoped
        # to the injected chip, not the node.
        ok = stub.Allocate(
            api.v1beta1_pb2.AllocateRequest(container_requests=[
                api.v1beta1_pb2.ContainerAllocateRequest(
                    devicesIDs=["accel1"])]), timeout=10)
        assert ok.container_responses[0].envs

        # Recovery: clear the token (inject_fault -r); the poller
        # must bring the chip back without a plugin restart.
        os.unlink(health_file)
        got = _wait_for_stream_health(
            stream, {"accel0": HEALTHY, "accel1": HEALTHY})
        assert got, "ListAndWatch never reported recovery"

        response = stub.Allocate(request, timeout=10)
        assert response.container_responses[0].envs


def _wait_for_stream_health(stream, want, max_updates=20):
    """Advance a ListAndWatch stream until it reports `want` (skipping
    intermediate updates); None if it never does."""
    for _ in range(max_updates):
        got = _health_by_id(next(stream))
        if got == want:
            return got
    return None
