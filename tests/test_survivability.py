# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Serving survivability: quarantine-and-rebuild, mid-stream replay,
circuit breaker, graceful drain, FIFO cancel purge, and the /readyz +
error-envelope HTTP contracts.

Drives the real ``_EngineService`` (and one real GenerationServer
over HTTP) with faults injected through the ``CEA_TPU_FAULT_PLAN``
seam — the same seam `make serving-chaos-check` uses, pinned here at
tier-1 granularity.
"""

import json
import queue
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.models import TransformerLM
from container_engine_accelerators_tpu.models.decode import (
    SlotDecodeEngine,
    decode,
)
from container_engine_accelerators_tpu.serving.server import (
    _Admission,
    _EngineService,
    _EngineWork,
)
from container_engine_accelerators_tpu.utils import faults


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def lm():
    # Same shape as test_slo_attribution's model: the engine
    # programs are already in the process jit cache by the time this
    # module runs in a full tier-1 pass.
    model = TransformerLM(vocab_size=48, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _factory(model, params, slots=3, slot_len=20):
    def build():
        return SlotDecodeEngine(model, params, slots=slots,
                                slot_len=slot_len, paged=True,
                                kv_block_size=4, buckets=[8, 16],
                                kv_quant="bf16", kv_spill=False)
    return build


def _work(prompt, p_len, new, seed=0, **kw):
    row = np.zeros((max(8, p_len),), np.int32)
    row[:p_len] = prompt[:p_len]
    return _EngineWork(row, p_len, new, 0.0, 0, 1.0, 0.0, 1.0, -1,
                       False, seed, None, **kw)


def _pool_is_clean(eng):
    pool = eng._pool
    pinned = set(eng._pinned)
    return (pool.free_count() == pool.usable - len(pinned)
            and pool.shared_count() == 0
            and pool.committed == 0
            and bool((eng._tables == eng._trash).all())
            and int(np.abs(pool.ref).sum()) == len(pinned))


def _events(name):
    return [e for e in obs.TRACER.snapshot()["events"]
            if e["name"] == name]


def _greedy_ref(model, params, prompts, news):
    width = max(len(p) for p in prompts)
    padded = np.zeros((len(prompts), width), np.int32)
    p_lens = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        padded[i, :len(p)] = p
        p_lens[i] = len(p)
    ref = np.asarray(decode(model, params, jnp.asarray(padded),
                            max(news), prompt_len=p_lens,
                            fast_prefill=False))
    return [ref[i, len(p):len(p) + n].tolist()
            for i, (p, n) in enumerate(zip(prompts, news))]


def _warm(svc, width=8):
    work = _work(np.zeros((width,), np.int32), width, 2,
                 account=False, no_prefix=True)
    assert svc.submit_many([work]) is not None
    status, out = work.done.get(timeout=600)
    assert status == "ok", out
    svc.reset_counters()


def test_step_fault_quarantines_rebuilds_and_replays(lm):
    """The tentpole contract: a device-side step failure quarantines
    the engine, rebuilds it through the factory, and REPLAYS every
    in-flight row as a forced prefix — greedy streams resume
    token-identical, the stall lands in the `recovery` bucket, the
    rebuilt pool is leak-free, and exactly one quarantine/recovered
    event pair is journaled."""
    model, params = lm
    q0, r0 = len(_events("serving.engine_quarantine")), len(
        _events("serving.engine_recovered"))
    svc = _EngineService(_factory(model, params)(), _Admission(0),
                         engine_factory=_factory(model, params))
    try:
        _warm(svc)
        prompts = [np.array([5, 6, 7, 8], np.int32),
                   np.array([9, 8, 7, 6, 5], np.int32),
                   np.array([11, 12, 13], np.int32)]
        news = [6, 5, 6]
        faults.install({"step": [2]})
        works = [_work(p, len(p), n, seed=i)
                 for i, (p, n) in enumerate(zip(prompts, news))]
        assert svc.submit_many(works) is not None
        for w in works:
            status, out = w.done.get(timeout=600)
            assert status == "ok", out
        faults.reset()
        ref = _greedy_ref(model, params, prompts, news)
        for w, want in zip(works, ref):
            assert w.tokens == want
        stats = svc.stats()
        assert stats["engine_state"] == "serving"
        assert stats["engine_rebuilds"] == 1
        assert stats["quarantine_episodes"] == 1
        assert len(_events("serving.engine_quarantine")) - q0 == 1
        assert len(_events("serving.engine_recovered")) - r0 == 1
        records = svc.debug_requests()["records"]
        assert sum(r["buckets"]["recovery"] for r in records) > 0
        for rec in records:
            total = sum(rec["buckets"].values())
            assert abs(total - rec["wall_s"]) <= max(
                0.01 * rec["wall_s"], 2e-5), rec
        assert _pool_is_clean(svc._engine)
    finally:
        svc.stop()


def test_prefill_fault_replays_with_zero_generated_tokens(lm):
    """An admission-time device failure rides the same episode shape:
    the failing row (no tokens yet) replays as a plain admission and
    still matches decode()."""
    model, params = lm
    svc = _EngineService(_factory(model, params)(), _Admission(0),
                         engine_factory=_factory(model, params))
    try:
        _warm(svc)
        faults.install({"prefill": [0]})
        prompt = np.array([3, 1, 4, 1], np.int32)
        work = _work(prompt, 4, 5)
        assert svc.submit_many([work]) is not None
        status, out = work.done.get(timeout=600)
        assert status == "ok", out
        faults.reset()
        assert work.tokens == _greedy_ref(model, params, [prompt],
                                          [5])[0]
        assert svc.stats()["engine_rebuilds"] == 1
        assert _pool_is_clean(svc._engine)
    finally:
        svc.stop()


def test_circuit_breaker_trips_sheds_and_reopens(lm, monkeypatch):
    """Repeated rebuild failures: retries with backoff, then the
    breaker opens (submissions shed, retry_after advertised, streams
    failed RETRYABLE), and a later successful factory probe closes
    it — one quarantine/recovered pair for the whole episode."""
    monkeypatch.setenv("CEA_TPU_ENGINE_REBUILD_RETRIES", "2")
    monkeypatch.setenv("CEA_TPU_ENGINE_REBUILD_BACKOFF_MS", "20")
    model, params = lm
    q0, r0 = len(_events("serving.engine_quarantine")), len(
        _events("serving.engine_recovered"))
    good = _factory(model, params)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if 2 <= calls["n"] <= 4:  # both retries + the first probe
            raise RuntimeError("factory down")
        return good()

    svc = _EngineService(flaky(), _Admission(0),
                         engine_factory=flaky)
    try:
        _warm(svc)
        faults.install({"step": [0]})
        stream_q = queue.Queue()
        work = _work(np.array([5, 6, 7, 8], np.int32), 4, 6,
                     stream_q=stream_q)
        assert svc.submit_many([work]) is not None
        while True:
            item = stream_q.get(timeout=120)
            if item[0] != "tok":
                break
        faults.reset()
        # The in-flight stream failed with the RETRYABLE envelope.
        assert item[0] == "error"
        assert item[2] is True
        assert svc.engine_state() == "breaker_open"
        assert svc.retry_after_s() >= 1
        assert not svc.ready()
        # Degraded: submissions shed while the breaker is open.
        assert svc.submit_many([_work(np.arange(1, 4), 3, 2)]) is None
        # The reopen probe (20ms-scale backoff) closes the breaker.
        deadline = time.monotonic() + 30
        while (svc.engine_state() != "serving"
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert svc.engine_state() == "serving"
        work2 = _work(np.array([9, 8, 7], np.int32), 3, 3)
        assert svc.submit_many([work2]) is not None
        status, out = work2.done.get(timeout=600)
        assert status == "ok", out
        # One episode end to end: exactly one event pair.
        assert len(_events("serving.engine_quarantine")) - q0 == 1
        assert len(_events("serving.engine_recovered")) - r0 == 1
    finally:
        svc.stop()


def test_cancelled_queued_request_purged_before_prefill(lm):
    """A client that disconnects while QUEUED is dropped from the
    FIFO without being admitted or prefilled, releasing its
    admission budget immediately — not after its whole queue
    transit."""
    model, params = lm
    # One slot + a 3-deep admission budget: w2/w3 queue behind w1.
    factory = _factory(model, params, slots=1)
    svc = _EngineService(factory(), _Admission(3),
                         engine_factory=factory)
    try:
        _warm(svc)
        prefills_before = None
        w1 = _work(np.array([5, 6, 7, 8], np.int32), 4, 12, seed=0)
        w2 = _work(np.array([1, 2, 3], np.int32), 3, 4, seed=1)
        w3 = _work(np.array([9, 9, 9], np.int32), 3, 4, seed=2)
        assert svc.submit_many([w1]) is not None
        assert svc.submit_many([w2]) is not None
        assert svc.submit_many([w3]) is not None
        # Budget exhausted: a fourth submission sheds...
        assert svc.submit_many(
            [_work(np.arange(1, 4), 3, 2)]) is None
        prefills_before = svc.stats()["engine_prefills"]
        # ...until the queued w3 cancels: its budget frees NOW,
        # while w1 is still decoding and w2 still queued.
        w3.cancel.set()
        status, out = w3.done.get(timeout=120)
        assert status == "error" and out == "cancelled"
        w4 = _work(np.array([4, 4, 4], np.int32), 3, 2, seed=3)
        assert svc.submit_many([w4]) is not None
        for w in (w1, w2, w4):
            status, out = w.done.get(timeout=600)
            assert status == "ok", out
        # The cancelled row was never prefilled (purged at the FIFO,
        # not admitted-and-retired): exactly w1 + w2 + w4 prefills.
        assert (svc.stats()["engine_prefills"] - prefills_before
                <= 3)
        rec = [r for r in svc.debug_requests()["records"]
               if r["outcome"] == "cancelled"]
        assert len(rec) == 1
        assert rec[0]["buckets"]["prefill"] == 0.0
    finally:
        svc.stop()


def test_drain_completes_inflight_and_sheds_new(lm):
    """Graceful drain: in-flight work runs to completion within the
    grace window; submissions after begin_drain shed; readiness
    flips immediately."""
    model, params = lm
    factory = _factory(model, params)
    svc = _EngineService(factory(), _Admission(0),
                         engine_factory=factory)
    try:
        _warm(svc)
        work = _work(np.array([7, 7, 2, 9], np.int32), 4, 8)
        assert svc.submit_many([work]) is not None
        assert svc.drain(grace_s=120) is True
        assert not svc.ready()
        assert svc.engine_state() == "draining"
        status, out = work.done.get(timeout=10)
        assert status == "ok", out
        assert svc.submit_many([_work(np.arange(1, 4), 3, 2)]) is None
    finally:
        svc.stop()


def test_bare_step_failure_releases_and_audits_pool(lm):
    """Satellite: WITHOUT a factory, a step failure fails the
    in-flight work (retryable), releases every slot/block/
    reservation, and the pool invariants hold — a poisoned arena
    does not keep serving with leaked capacity."""
    model, params = lm
    eng = _factory(model, params)()
    svc = _EngineService(eng, _Admission(0))  # unsupervised
    try:
        _warm(svc)
        faults.install({"step": [1]})
        stream_q = queue.Queue()
        work = _work(np.array([5, 6, 7, 8], np.int32), 4, 6,
                     stream_q=stream_q)
        assert svc.submit_many([work]) is not None
        while True:
            item = stream_q.get(timeout=120)
            if item[0] != "tok":
                break
        faults.reset()
        assert item[0] == "error"
        assert item[2] is True  # transient device fault: retryable
        # Same engine (no rebuild), pool back to clean.
        assert svc._engine is eng
        assert eng.pool_leak_report() is None
        assert _pool_is_clean(eng)
        assert svc.stats()["engine_rebuilds"] == 0
        # And the service keeps serving.
        work2 = _work(np.array([1, 2, 3], np.int32), 3, 3)
        assert svc.submit_many([work2]) is not None
        status, out = work2.done.get(timeout=600)
        assert status == "ok", out
    finally:
        svc.stop()


def test_force_reclaim_restores_torn_pool(lm):
    """Engine-level: a row abandoned mid-flight (the torn state a
    device fault leaves) is fully reclaimed — blocks, reservations,
    tables — by force_reclaim, and pool_leak_report names the tear
    first."""
    model, params = lm
    eng = _factory(model, params)()
    eng.admit(np.array([5, 6, 7, 8], np.int32), 4, max_new=4)
    leaks = eng.pool_leak_report()
    assert leaks is not None and "active_rows" in leaks
    assert eng.force_reclaim() is None
    assert _pool_is_clean(eng)


def test_fault_plan_parsing_and_counting(monkeypatch):
    """The CEA_TPU_FAULT_PLAN seam: env JSON parse, validation, and
    deterministic index counting."""
    with pytest.raises(ValueError):
        faults.FaultPlan({"bogus_op": [1]})
    with pytest.raises(ValueError):
        faults.FaultPlan({"step": [-1]})
    plan = faults.install({"step": [1]})
    faults.fire("step")                    # index 0: clean
    with pytest.raises(faults.InjectedFault):
        faults.fire("step")                # index 1: fires
    faults.fire("step")                    # index 2: clean again
    assert plan.fired() == {"step": [1]}
    assert plan.counts()["step"] == 3
    faults.reset()
    monkeypatch.setenv("CEA_TPU_FAULT_PLAN",
                       json.dumps({"hydrate": [0]}))
    assert faults.active().pending() == {"hydrate": [0]}
    faults.reset()


# ---------------------------------------------------------------------
# HTTP lifecycle: /readyz transitions, Retry-After, error envelope.
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def gen_server(lm):
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )

    model, params = lm
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=2,
                           buckets=[8], warm=True)
    srv.start()
    yield srv
    srv.stop()


def _get(server, path):
    try:
        with urllib.request.urlopen(
                f"http://localhost:{server.port}{path}",
                timeout=30) as resp:
            return resp.status, dict(resp.headers), json.loads(
                resp.read())
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), json.loads(err.read())


def _post(server, payload):
    req = urllib.request.Request(
        f"http://localhost:{server.port}/v1/models/lm:generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), err.read()


def test_readyz_transitions_and_drain_contract(gen_server):
    """/readyz mirrors the service lifecycle while /healthz stays
    live: ready -> drain flips /readyz to 503 (Retry-After attached)
    the same instant, /healthz keeps answering 200, and POSTs 503."""
    code, _, body = _get(gen_server, "/readyz")
    assert code == 200 and body["status"] == "ready"
    code, _, _ = _get(gen_server, "/healthz")
    assert code == 200
    stats = gen_server.stats()
    assert stats["engine_state"] == "serving"
    gen_server.begin_drain()
    try:
        code, headers, body = _get(gen_server, "/readyz")
        assert code == 503
        assert body["status"] == "draining"
        assert int(headers["Retry-After"]) >= 1
        # Liveness unchanged: restarting the pod would not help.
        code, _, _ = _get(gen_server, "/healthz")
        assert code == 200
        code, headers, raw = _post(gen_server,
                                   {"prompts": [[1, 2, 3]],
                                    "max_new_tokens": 2})
        assert code == 503
        assert int(headers["Retry-After"]) >= 1
        assert "request_id" in json.loads(raw)
    finally:
        gen_server._draining = False
        if gen_server._engine_service is not None:
            with gen_server._engine_service._lock:
                gen_server._engine_service._draining = False
    code, _, _ = _get(gen_server, "/readyz")
    assert code == 200


def test_stream_error_envelope_over_http(gen_server):
    """Satellite: a mid-stream engine failure emits a final ndjson
    error ENVELOPE — {"error", "retryable", "request_id"} — instead
    of dropping the socket. (Supervision is disabled for the request
    so the fault surfaces as a stream error, not a recovery.)"""
    svc = gen_server._engine_service
    saved = svc._engine_factory
    svc._engine_factory = None
    faults.install({"step": [1]})
    try:
        code, _, raw = _post(gen_server,
                             {"prompts": [[5, 6, 7]],
                              "max_new_tokens": 6, "stream": True})
        assert code == 200
        lines = [json.loads(l) for l in raw.decode().splitlines()]
        assert lines, "empty stream body"
        last = lines[-1]
        assert "error" in last
        assert last["retryable"] is True
        assert last["request_id"]
    finally:
        faults.reset()
        svc._engine_factory = saved
        # The bare-path failure released everything; service serves.
    code, _, raw = _post(gen_server, {"prompts": [[5, 6, 7]],
                                      "max_new_tokens": 2})
    assert code == 200


def test_stream_resumes_through_quarantine_over_http(gen_server):
    """End to end over HTTP: with supervision on, a mid-stream fault
    is INVISIBLE to the client — the stream stalls, resumes, and the
    tokens match the same request served fault-free."""
    payload = {"prompts": [[4, 2, 4, 2]], "max_new_tokens": 6,
               "stream": True}
    code, _, raw = _post(gen_server, payload)
    assert code == 200
    clean = [t for line in raw.decode().splitlines()
             for t in json.loads(line).get("tokens", [])]
    faults.install({"step": [2]})
    try:
        code, _, raw = _post(gen_server, payload)
    finally:
        faults.reset()
    assert code == 200
    lines = [json.loads(l) for l in raw.decode().splitlines()]
    assert not any("error" in l for l in lines), lines
    faulted = [t for l in lines for t in l.get("tokens", [])]
    assert faulted == clean
