# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Straggler detection: sliding-window skew, event hysteresis, the
skew gauge on the Prometheus surface, journal replay, and the
multihost-sim train-loop integration."""

import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.obs.straggler import (
    SKEW_GAUGE,
    StragglerDetector,
    scan_events,
)


@pytest.fixture(autouse=True)
def clean_tracer():
    obs.TRACER.reset()
    yield
    obs.TRACER.reset()


def _drive(det, steps, times_by_host):
    for _ in range(steps):
        for host, t in times_by_host.items():
            det.observe(host, t)


def test_detects_one_slow_host_exactly_once():
    tracer = obs.Tracer(enabled=True)
    det = StragglerDetector(window=16, factor=1.5, min_samples=4,
                            tracer=tracer)
    # host3 runs 2.5x the fleet median, persistently, many windows.
    _drive(det, 50, {"host0": 0.10, "host1": 0.10, "host2": 0.10,
                     "host3": 0.25})
    events = [e for e in tracer.snapshot()["events"]
              if e["name"] == "straggler.detected"]
    assert len(events) == 1  # hysteresis: one event per episode
    f = events[0]["fields"]
    assert f["host"] == "host3"
    assert f["skew_ratio"] == pytest.approx(2.5, rel=0.05)
    assert det.flagged() == ["host3"]
    # The gauge is live and nonzero for every host, >1.5 for host3.
    gauges = {labels: v for (name, labels), v
              in tracer.gauges().items() if name == SKEW_GAUGE}
    assert gauges[(("host", "host3"),)] > 1.5
    assert gauges[(("host", "host0"),)] == pytest.approx(1.0,
                                                         rel=0.05)


def test_recovery_emits_event_and_rearms():
    tracer = obs.Tracer(enabled=True)
    det = StragglerDetector(window=8, factor=1.5, min_samples=4,
                            tracer=tracer)
    _drive(det, 20, {"h0": 0.1, "h1": 0.1, "h2": 0.3})
    assert det.event_count() == 1
    _drive(det, 20, {"h0": 0.1, "h1": 0.1, "h2": 0.1})  # recovers
    assert det.flagged() == []
    names = [e["name"] for e in tracer.snapshot()["events"]]
    assert names.count("straggler.recovered") == 1
    _drive(det, 20, {"h0": 0.1, "h1": 0.1, "h2": 0.3})  # relapse
    assert det.event_count() == 2


def test_no_detection_below_min_samples_or_single_host():
    tracer = obs.Tracer(enabled=True)
    det = StragglerDetector(window=16, factor=1.5, min_samples=8,
                            tracer=tracer)
    _drive(det, 3, {"h0": 0.1, "h1": 0.9})  # too few samples
    assert det.skews() == {}
    solo = StragglerDetector(window=16, factor=1.5, min_samples=2,
                             tracer=tracer)
    _drive(solo, 20, {"only": 0.5})  # skew against yourself: no-op
    assert solo.skews() == {}
    assert solo.event_count() == 0


def test_scan_events_replays_merged_journals():
    """The offline path (tpu_diagnose bundles): per-host
    train.step_summary events from merged journals reproduce the
    live detector's verdict."""
    events = []
    for step in range(1, 13):
        for host, p50 in (("host0", 100.0), ("host1", 102.0),
                          ("host2", 240.0)):
            events.append({"name": "train.step_summary",
                           "unix": 1000.0 + step,
                           "fields": {"host": host, "step": step,
                                      "step_time_p50_ms": p50,
                                      "data_wait_p50_ms": 1.0}})
    events.append({"name": "health.transition", "unix": 999.0,
                   "fields": {"device": "accel0"}})  # ignored
    det = scan_events(events, window=8, factor=1.5, min_samples=4,
                      tracer=obs.Tracer(enabled=False))
    assert det.flagged() == ["host2"]
    assert det.skews()["host2"] == pytest.approx(240 / 102, rel=0.05)


# -- multihost-sim train loop -----------------------------------------

def test_synthetic_slow_host_in_multihost_sim_train_loop():
    """Acceptance: a synthetic slow host in a multihost-sim train
    loop triggers exactly one straggler.detected event and a nonzero
    tpu_train_step_skew_ratio gauge. Each simulated host runs a REAL
    Trainer over a slice of the virtual CPU mesh (one train step
    program per host, same model), with the slow host's step padded
    by a sleep — the per-host Trainer telemetry feeds one shared
    detector the way one aggregator would consume the fleet's
    journals."""
    import time

    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from container_engine_accelerators_tpu.parallel.train import (
        Trainer,
        cross_entropy_loss,
    )

    detector = StragglerDetector(window=8, factor=1.5, min_samples=4)

    def apply_fn(variables, images, train):
        logits = images.reshape(images.shape[0], -1) @ \
            variables["params"]["w"]
        return logits, {}

    devices = np.array(jax.devices()[:4]).reshape(4, 1)
    hosts = []
    for idx in range(4):
        mesh = Mesh(devices[idx:idx + 1], ("data", "model"))
        trainer = Trainer(apply_fn, cross_entropy_loss,
                          optax.sgd(0.1), mesh=mesh,
                          donate_state=False,
                          host_id=f"host{idx}", summary_every=4)
        state = trainer.init_state(
            {"params": {"w": np.zeros((4, 2), np.float32)}})
        hosts.append((trainer, state))

    batch = (np.ones((2, 2, 2), np.float32),
             np.zeros((2,), np.int32))
    # Warm every host's compiled step BEFORE attaching the detector
    # (the first dispatch pays the lazy XLA compile — a real fleet's
    # steady-state windows never contain it), then give every host a
    # uniform synthetic device-step cost with host3 3x slower — the
    # slowness lands inside the measured step, as a slow chip's
    # would. The baseline matters: bare dispatch is microseconds and
    # its scheduling noise would swamp any ratio.
    def with_device_cost(step_fn, seconds):
        def stalled(state, batch):
            time.sleep(seconds)
            return step_fn(state, batch)
        return stalled

    for idx, (trainer, state) in enumerate(hosts):
        new_state, _ = trainer.train_step(state, batch)
        hosts[idx] = (trainer, new_state)
        trainer._straggler = detector
        trainer._train_step = with_device_cost(
            trainer._train_step, 0.03 if idx == 3 else 0.01)

    for step in range(16):
        for idx, (trainer, state) in enumerate(hosts):
            new_state, _ = trainer.train_step(state, batch)
            hosts[idx] = (trainer, new_state)

    events = [e for e in obs.TRACER.snapshot()["events"]
              if e["name"] == "straggler.detected"]
    assert len(events) == 1, events
    assert events[0]["fields"]["host"] == "host3"
    gauges = {labels: v for (name, labels), v
              in obs.TRACER.gauges().items() if name == SKEW_GAUGE}
    assert gauges[(("host", "host3"),)] > 1.5
    # Per-host summaries landed in the journal for offline replay.
    summaries = [e for e in obs.TRACER.snapshot()["events"]
                 if e["name"] == "train.step_summary"]
    assert {e["fields"]["host"] for e in summaries} == {
        f"host{i}" for i in range(4)}
