# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Paged KV-cache block pool (SlotDecodeEngine paged mode).

The paged pool's correctness contract stacks on the engine's: greedy
streams stay token-identical to per-request ``decode`` WHILE the
physical cache is block-scattered, prefix-shared, and copy-on-write
forked under the rows. These tests drive the engine directly on
tier-1-sized models; the serving loop's paged behavior rides
test_serving.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import (
    MoETransformerLM,
    TransformerLM,
)
from container_engine_accelerators_tpu.models.decode import (
    SlotDecodeEngine,
    _paged_insert_impl,
    _paged_step_impl,
    decode,
    greedy_decode,
)


def _make_lm(**kw):
    kwargs = dict(vocab_size=48, embed_dim=32, num_layers=2,
                  num_heads=4, max_seq_len=32, dtype=jnp.float32)
    kwargs.update(kw)
    model = TransformerLM(**kwargs)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def lm():
    return _make_lm()


def _paged(model, params, slots=3, slot_len=14, block_size=4,
           **kw):
    return SlotDecodeEngine(model, params, slots=slots,
                            slot_len=slot_len, paged=True,
                            kv_block_size=block_size, **kw)


def _pool_is_clean(eng):
    """Refcount exactness: every non-pinned block free, no shared
    blocks, no outstanding commitment, every table row all-trash."""
    pool = eng._pool
    pinned = set(eng._pinned)
    return (pool.free_count() == pool.usable - len(pinned)
            and pool.shared_count() == 0
            and pool.committed == 0
            and bool((eng._tables == eng._trash).all())
            and int(np.abs(pool.ref).sum()) == len(pinned))


def test_staggered_shared_prefix_matches_decode(lm):
    """Three rows admitted at different steps, two sharing a long
    prompt prefix (full blocks refcounted + the partial boundary
    block COW-forked): every greedy stream is exactly its per-request
    decode() stream, and the prefix index actually hit."""
    model, params = lm
    eng = _paged(model, params, slots=3, slot_len=16)
    base = np.array([5, 6, 7, 8, 9, 10], np.int32)       # plen 6
    other = np.array([20, 21, 22, 23, 24, 25], np.int32)

    s1, f1, _, _ = eng.admit(base, 6)
    o1 = [f1]
    for _ in range(2):
        toks, _ = eng.step()
        o1.append(int(toks[s1]))
    s2, f2, _, _ = eng.admit(base, 6)   # prefix hit: 1 full + 1 fork
    s3, f3, _, _ = eng.admit(other, 6)  # no hit
    assert eng.kv_block_stats()["prefix_hits"] == 1
    assert eng.kv_block_stats()["kv_blocks_shared"] >= 1
    o2, o3 = [f2], [f3]
    for _ in range(4):
        toks, _ = eng.step()
        o1.append(int(toks[s1]))
        o2.append(int(toks[s2]))
        o3.append(int(toks[s3]))
    refs = np.asarray(greedy_decode(
        model, params, jnp.asarray(np.stack([base, base, other])), 7))
    assert o1 == refs[0, 6:13].tolist()
    assert o2 == refs[1, 6:11].tolist()
    assert o3 == refs[2, 6:11].tolist()
    for s in (s1, s2, s3):
        eng.release(s)
    assert _pool_is_clean(eng)


def test_cow_isolation_between_forked_rows(lm):
    """Two rows forked from one shared prefix never see each other's
    writes: both decode independently past the fork point and match
    their OWN per-request references, including the donor, which
    keeps writing generated K/V into the partial block it donated."""
    model, params = lm
    eng = _paged(model, params, slots=2, slot_len=16)
    shared = np.array([3, 1, 4, 1, 5, 9], np.int32)       # plen 6
    sa = np.concatenate([shared, [11]]).astype(np.int32)  # plen 7
    sb = np.concatenate([shared, [17]]).astype(np.int32)  # plen 7

    slot_a, fa, _, _ = eng.admit(sa, 7)
    # The donor writes generated tokens INTO its partial prompt block
    # before the second row forks it.
    oa = [fa]
    toks, _ = eng.step()
    oa.append(int(toks[slot_a]))
    slot_b, fb, _, _ = eng.admit(sb, 7)   # forks the partial block
    assert eng.kv_block_stats()["prefix_hits"] == 1
    ob = [fb]
    for _ in range(4):
        toks, _ = eng.step()
        oa.append(int(toks[slot_a]))
        ob.append(int(toks[slot_b]))
    ref = np.asarray(greedy_decode(
        model, params, jnp.asarray(np.stack([sa, sb])), 6))
    assert oa == ref[0, 7:13].tolist()
    assert ob == ref[1, 7:12].tolist()
    eng.release(slot_a)
    eng.release(slot_b)
    assert _pool_is_clean(eng)


def test_refcounts_exact_across_recycling_and_cancel(lm):
    """EOS-style retirement and mid-stream cancel (both are
    release()) drop every block reference exactly once: after any
    admission/release interleaving the pool returns to all-free with
    zero refcounts — no leak, no double free."""
    model, params = lm
    eng = _paged(model, params, slots=2, slot_len=16)
    shared = np.array([2, 4, 6, 8, 10, 12], np.int32)
    s1, _, _, _ = eng.admit(shared, 6)
    s2, _, _, _ = eng.admit(shared, 6)            # shares s1's blocks
    eng.step()
    eng.release(s1)                               # donor retires first
    # The survivor's shared blocks stay resident (ref 1, not freed).
    assert eng.kv_block_stats()["kv_blocks_free"] < eng._pool.usable
    eng.step()                                    # survivor still live
    s3, _, _, _ = eng.admit(shared, 6)            # revives/shares again
    eng.step()
    eng.release(s3)                               # "cancel" mid-stream
    eng.release(s2)
    assert _pool_is_clean(eng)
    # Freed-but-indexed blocks revive: a fresh admission of the same
    # prompt still hits the index without any resident row.
    before = eng.kv_block_stats()["prefix_hits"]
    s4, _, _, _ = eng.admit(shared, 6)
    assert eng.kv_block_stats()["prefix_hits"] == before + 1
    eng.release(s4)
    assert _pool_is_clean(eng)


def test_exhaustion_queues_admission_without_corruption(lm):
    """A pool too small for another row refuses admission
    (can_admit False, admit raises) and the resident rows' tables
    stay intact: their streams stay exact through the refusal, and
    after a release the queued admission lands and is exact too."""
    model, params = lm
    # 2 slots but only one row's worth of blocks (+trash): the
    # second admission must queue on BLOCKS, not slots.
    eng = _paged(model, params, slots=2, slot_len=12,
                 block_size=4, kv_blocks=4)
    pa = np.array([1, 2, 3, 4], np.int32)
    pb = np.array([9, 8, 7, 6], np.int32)
    slot_a, fa, _, _ = eng.admit(pa, 4, max_new=8)
    assert eng.free_slots() == 1
    assert not eng.can_admit(pb, 4, 8)
    with pytest.raises(RuntimeError, match="KV block"):
        eng.admit(pb, 4, max_new=8)
    oa = [fa]
    for _ in range(5):
        toks, _ = eng.step()
        oa.append(int(toks[slot_a]))
    ref_a = np.asarray(greedy_decode(
        model, params, jnp.asarray(pa[None]), 6))[0]
    assert oa == ref_a[4:10].tolist()
    eng.release(slot_a)
    assert eng.can_admit(pb, 4, 8)
    slot_b, fb, _, _ = eng.admit(pb, 4, max_new=8)
    ob = [fb]
    for _ in range(5):
        toks, _ = eng.step()
        ob.append(int(toks[slot_b]))
    ref_b = np.asarray(greedy_decode(
        model, params, jnp.asarray(pb[None]), 6))[0]
    assert ob == ref_b[4:10].tolist()
    eng.release(slot_b)
    assert _pool_is_clean(eng)


def test_dense_fallback_parity(lm, monkeypatch):
    """CEA_TPU_PAGED_KV=0 restores the dense pool bit-for-bit: same
    slots, same stream, no paged state; and the env default is paged
    when unset."""
    model, params = lm
    prompt = np.array([1, 2, 3, 4], np.int32)
    monkeypatch.setenv("CEA_TPU_PAGED_KV", "0")
    dense = SlotDecodeEngine(model, params, slots=2, slot_len=14)
    assert not dense.paged
    assert dense.kv_block_stats() is None
    monkeypatch.delenv("CEA_TPU_PAGED_KV")
    paged = SlotDecodeEngine(model, params, slots=2, slot_len=14,
                             kv_block_size=4)
    assert paged.paged
    outs = []
    for eng in (dense, paged):
        slot, first, _, _ = eng.admit(prompt, 4)
        out = [first]
        for _ in range(5):
            toks, _ = eng.step()
            out.append(int(toks[slot]))
        eng.release(slot)
        outs.append(out)
    assert outs[0] == outs[1]
    ref = np.asarray(greedy_decode(
        model, params, jnp.asarray(prompt[None]), 6))[0]
    assert outs[0] == ref[4:10].tolist()


def test_one_step_program_for_all_paged_traffic(lm):
    """The PR 4 program-count bound holds on the paged pool: one
    jitted step program serves every traffic mix (greedy + filtered
    sampling + penalties + prefix-shared rows + COW forks + block-
    boundary growth), and one insert program serves every
    admission."""
    model, params = lm
    step0 = _paged_step_impl._cache_size()
    ins0 = _paged_insert_impl._cache_size()
    # A pool shape no other test uses: the jit caches are process-
    # global, so a shape-colliding earlier test would hide compiles.
    eng = _paged(model, params, slots=4, slot_len=16)
    shared = np.array([4, 5, 6, 7, 8, 9], np.int32)
    eng.admit(shared, 6)
    eng.step()
    eng.admit(shared, 6, temperature=0.9, top_k=7, top_p=0.9,
              min_p=0.01, seed=3)
    eng.admit(np.array([30, 31, 32], np.int32), 3,
              repetition_penalty=1.5)
    for _ in range(6):   # crosses block boundaries (bs=4)
        eng.step()
    assert _paged_step_impl._cache_size() - step0 == 1
    assert _paged_insert_impl._cache_size() - ins0 == 1


def test_pin_prefix_system_prompt_serving(lm):
    """pin_prefix keeps a system prompt's blocks resident without a
    slot; admissions prefix-hit it and their greedy streams equal
    decode(prefix + suffix); releasing every row leaves exactly the
    pinned blocks held."""
    model, params = lm
    eng = _paged(model, params, slots=2, slot_len=20,
                 buckets=[4], pin_reserve_tokens=6)
    prefix = np.array([7, 11, 13, 17, 19, 23], np.int32)  # 6 tokens
    pinned = eng.pin_prefix(prefix)
    assert pinned == 2                                    # bs=4
    # The default arena reserved the pin's span on top of the rows'
    # worst case, so even a full pool of worst-case rows can admit
    # (the review-caught 1-slot wedge: pinned blocks ate the only
    # row's budget and the queue waited forever).
    worst = np.concatenate([prefix, np.array([1, 2, 3, 4], np.int32)])
    assert eng.can_admit(worst, 10, eng.slot_len - 10)
    suffix = np.array([1, 2, 3], np.int32)
    full = np.concatenate([prefix, suffix])
    slot, first, _, _ = eng.admit(full, 9)
    assert eng.kv_block_stats()["prefix_hits"] == 1
    out = [first]
    for _ in range(4):
        toks, _ = eng.step()
        out.append(int(toks[slot]))
    ref = np.asarray(greedy_decode(
        model, params, jnp.asarray(full[None]), 5))[0]
    assert out == ref[9:14].tolist()
    eng.release(slot)
    assert _pool_is_clean(eng)
    assert eng.kv_block_stats()["kv_blocks_free"] == (
        eng._pool.usable - pinned)


def test_paged_moe_and_int8_cache(lm):
    """The block pool composes with the MoE family and the int8 KV
    cache (quantized arena + scale blocks): greedy streams stay
    exact against per-request decode."""
    del lm
    for model, params in (
            (lambda m: (m, m.init(jax.random.PRNGKey(1),
                                  jnp.zeros((1, 8), jnp.int32))
                        ["params"]))(MoETransformerLM(
                            vocab_size=48, embed_dim=32,
                            num_layers=2, num_heads=4,
                            num_experts=2, max_seq_len=32,
                            dtype=jnp.float32)),
            _make_lm(kv_cache_dtype="int8", pos_embedding="rope")):
        eng = _paged(model, params, slots=2, slot_len=14)
        shared = np.array([5, 6, 7, 8, 9], np.int32)
        s1, f1, _, _ = eng.admit(shared, 5)
        s2, f2, _, _ = eng.admit(shared, 5)
        assert eng.kv_block_stats()["prefix_hits"] == 1
        o1, o2 = [f1], [f2]
        for _ in range(4):
            toks, _ = eng.step()
            o1.append(int(toks[s1]))
            o2.append(int(toks[s2]))
        ref = np.asarray(greedy_decode(
            model, params, jnp.asarray(shared[None]), 5))[0]
        assert o1 == ref[5:10].tolist()
        assert o2 == ref[5:10].tolist()
        eng.release(s1)
        eng.release(s2)
        assert _pool_is_clean(eng)


def test_paged_score_and_logprobs_consume_no_blocks(lm):
    """Scoring rides the prefill program only — no slot, no blocks —
    and matches decode's echo; an admission needing full echo
    (allow_prefix=False) skips sharing and still matches."""
    model, params = lm
    eng = _paged(model, params, slots=1, slot_len=14)
    prompt = np.array([2, 4, 6, 8], np.int32)
    echo = eng.score(prompt, 4)
    assert eng.free_slots() == 1
    assert _pool_is_clean(eng)
    _, lps_ref = decode(model, params, jnp.asarray(prompt[None]), 1,
                        return_logprobs=True)
    np.testing.assert_allclose(echo[:4], np.asarray(lps_ref)[0][:4],
                               atol=1e-4)
    # Echo-bearing admission after an identical prompt is resident:
    # sharing must NOT eat the echo region.
    slot, _, _, _ = eng.admit(prompt, 4)
    eng.release(slot)
    slot, tok0, lp0, echo2 = eng.admit(prompt, 4,
                                       allow_prefix=False)
    lps = list(echo2[:4]) + [lp0]
    for _ in range(3):
        _, lp = eng.step()
        lps.append(float(lp[slot]))
    _, ref = decode(model, params, jnp.asarray(prompt[None]), 5,
                    return_logprobs=True)
    np.testing.assert_allclose(np.asarray(lps),
                               np.asarray(ref)[0][:8], atol=1e-4)
    eng.release(slot)
