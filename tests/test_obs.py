# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Unified tracing layer tests: tracer core, exporters, HTTP surface,
and the cross-layer threading (plugin scrape merge, serving span
tree, trace_dump tool)."""

import json
import os
import urllib.request

import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.obs.trace import Tracer


@pytest.fixture(autouse=True)
def clean_tracer():
    """Tests share the process-wide tracer; isolate journal state."""
    obs.TRACER.reset()
    yield
    obs.TRACER.reset()


# -- tracer core ------------------------------------------------------

def test_span_nesting_and_journal():
    with obs.span("outer", kind="test") as outer:
        with obs.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.trace_id == outer.trace_id
    snap = obs.TRACER.snapshot()
    names = [s["name"] for s in snap["spans"]]
    # Children close (and record) before parents.
    assert names == ["inner", "outer"]
    assert snap["spans"][1]["parent_id"] is None
    assert snap["spans"][0]["duration_s"] >= 0
    assert not snap["open_spans"]


def test_explicit_parent_crosses_threads():
    import threading

    ctxs = {}
    with obs.span("request") as req:
        ctxs["parent"] = req.context()

        def worker():
            with obs.span("batch", parent=ctxs["parent"]):
                with obs.span("decode"):
                    pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    spans = {s["name"]: s for s in obs.TRACER.snapshot()["spans"]}
    assert spans["batch"]["parent_id"] == spans["request"]["span_id"]
    assert spans["decode"]["parent_id"] == spans["batch"]["span_id"]
    assert (spans["decode"]["trace_id"]
            == spans["request"]["trace_id"])


def test_error_status_and_attrs():
    with pytest.raises(ValueError):
        with obs.span("boom", a=1) as sp:
            sp.set(b=2)
            raise ValueError("nope")
    rec = obs.TRACER.snapshot()["spans"][0]
    assert rec["status"] == "error"
    assert rec["attrs"]["a"] == 1
    assert rec["attrs"]["b"] == 2
    assert "nope" in rec["attrs"]["error"]


def test_ring_buffer_bounds_memory():
    tracer = Tracer(capacity=10, enabled=True)
    for i in range(50):
        with tracer.span(f"s{i}"):
            pass
        tracer.event(f"e{i}")
    snap = tracer.snapshot()
    assert len(snap["spans"]) == 10
    assert len(snap["events"]) == 10
    assert snap["dropped_spans"] == 40
    assert snap["dropped_events"] == 40
    # The ring keeps the NEWEST entries.
    assert snap["spans"][-1]["name"] == "s49"


def test_disabled_tracer_allocates_nothing():
    tracer = Tracer(enabled=False)
    sp = tracer.span("hot")
    assert sp is obs.NULL_SPAN  # the singleton, not a new object
    with sp:
        sp.set(x=1)
    tracer.event("nope", x=1)
    snap = tracer.snapshot()
    assert not snap["spans"] and not snap["events"]
    # Histograms still record: they are the /metrics surface.
    tracer.histogram("h").observe(0.5)
    assert tracer.histogram("h").count == 1


def test_events_carry_fields_and_context():
    with obs.span("op") as sp:
        obs.event("decision", device="accel0", reason="test")
    ev = obs.TRACER.snapshot()["events"][0]
    assert ev["name"] == "decision"
    assert ev["fields"] == {"device": "accel0", "reason": "test"}
    assert ev["trace_id"] == sp.trace_id


# -- histograms -------------------------------------------------------

def test_histogram_buckets_and_quantiles():
    h = obs.Histogram("lat", buckets=(0.1, 1.0, 10.0))
    assert h.quantile(0.5) is None
    for v in (0.05, 0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    counts, total, n = h.snapshot()
    assert counts == [2, 2, 1, 0]
    assert n == 5
    assert total == pytest.approx(6.1)
    assert 0 < h.quantile(0.5) <= 1.0
    assert 1.0 < h.quantile(0.99) <= 10.0
    h.observe(99.0)  # lands in +Inf; quantile stays finite
    assert h.quantile(1.0) == 10.0


def test_histogram_quantile_edge_cases():
    """The /stats percentiles now back SLO reporting — pin the
    interpolation's corners: empty, single observation, q=0/q=1,
    and an all-overflow histogram."""
    h = obs.Histogram("edge", buckets=(1.0, 2.0, 4.0))
    assert h.quantile(0.0) is None and h.quantile(1.0) is None

    # Single observation in (1, 2]: every quantile stays inside the
    # owning bucket; q=0 pins its lower bound, q=1 its upper.
    h.observe(1.5)
    assert h.quantile(0.0) == pytest.approx(1.0)
    assert h.quantile(1.0) == pytest.approx(2.0)
    assert 1.0 <= h.quantile(0.5) <= 2.0

    # q=0 with a populated FIRST bucket starts from 0 (the implicit
    # lower bound), and q=1 reaches the last populated bound.
    h2 = obs.Histogram("edge2", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0):
        h2.observe(v)
    assert h2.quantile(0.0) == pytest.approx(0.0)
    assert h2.quantile(1.0) == pytest.approx(4.0)
    # Monotone in q, always within [0, largest bound].
    qs = [h2.quantile(q / 10) for q in range(11)]
    assert qs == sorted(qs)
    assert all(0.0 <= v <= 4.0 for v in qs)

    # All-overflow: every observation past the largest finite bound
    # reports that bound (an upper-bound-less estimate is a lie).
    h3 = obs.Histogram("edge3", buckets=(1.0, 2.0))
    for _ in range(5):
        h3.observe(100.0)
    for q in (0.0, 0.5, 1.0):
        assert h3.quantile(q) == 2.0

    # Zero-count buckets between populated ones don't distort the
    # rank walk (the `and c` guard).
    h4 = obs.Histogram("edge4", buckets=(1.0, 2.0, 4.0, 8.0))
    h4.observe(0.5)
    h4.observe(7.0)  # buckets 2 and 3 empty in between
    assert h4.quantile(0.5) == pytest.approx(1.0)
    assert 4.0 <= h4.quantile(0.99) <= 8.0


def test_prometheus_text_format():
    tracer = Tracer(enabled=True)
    h = tracer.histogram("x_seconds", "help text",
                         labels={"method": "Allocate"},
                         buckets=(0.5, 1.0))
    h.observe(0.2)
    h.observe(2.0)
    tracer.counter("y_total", 3, kind="a")
    text = obs.prometheus_text(tracer)
    assert "# TYPE x_seconds histogram" in text
    assert 'x_seconds_bucket{le="0.5",method="Allocate"} 1' in text
    assert 'x_seconds_bucket{le="+Inf",method="Allocate"} 2' in text
    assert 'x_seconds_count{method="Allocate"} 2' in text
    assert 'y_total{kind="a"} 3' in text


def test_gauges_export_and_reset():
    obs.gauge("tpu_train_step_skew_ratio", 1.75, host="host3")
    obs.gauge("tpu_train_step_skew_ratio", 0.98, host="host0")
    text = obs.prometheus_text(obs.TRACER)
    assert "# TYPE tpu_train_step_skew_ratio gauge" in text
    assert ('tpu_train_step_skew_ratio{host="host3"} 1.75'
            in text)
    varz = obs.varz(obs.TRACER)
    assert varz["gauges"]['tpu_train_step_skew_ratio{host="host0"}'] \
        == 0.98
    # Gauges go DOWN too (unlike counters) and clear on reset.
    obs.gauge("tpu_train_step_skew_ratio", 1.0, host="host3")
    assert obs.TRACER.gauges()[
        ("tpu_train_step_skew_ratio", (("host", "host3"),))] == 1.0
    obs.TRACER.reset()
    assert not obs.TRACER.gauges()


def test_snapshot_carries_identity_stamp():
    snap = obs.TRACER.snapshot()
    ident = snap["identity"]
    assert ident["pid"] == os.getpid()
    assert ident["host"] and isinstance(ident["role"], str)
    assert obs.process_label(ident).endswith(f"[{os.getpid()}]")


# -- perfetto export --------------------------------------------------

def test_perfetto_trace_event_shape():
    with obs.span("parent", layer="serving"):
        with obs.span("child"):
            pass
        obs.event("marker", n=1)
    doc = obs.perfetto_trace(obs.TRACER.snapshot())
    assert "traceEvents" in doc
    complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["name"] for e in complete} == {"parent", "child"}
    for e in complete:
        assert e["ts"] > 0 and e["dur"] >= 0
        assert isinstance(e["pid"], int)
        assert "span_id" in e["args"]
    assert instants[0]["name"] == "marker"
    assert metas and metas[0]["name"] == "thread_name"
    json.dumps(doc)  # must be JSON-serializable end to end


# -- plugin HTTP surface ----------------------------------------------

def test_metric_server_debug_endpoints_and_scrape_merge(fake_node):
    from container_engine_accelerators_tpu.chip import PyChipBackend
    from container_engine_accelerators_tpu.plugin.manager import (
        TpuManager,
    )
    from container_engine_accelerators_tpu.plugin.metrics import (
        MetricServer,
    )

    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("1x2")
    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=fake_node.dev_dir,
                     state_dir=fake_node.state_dir, backend=backend)
    mgr.start()
    server = MetricServer(mgr, backend, port=0,
                          pod_resources_socket="/nonexistent")
    server.start()
    try:
        with obs.span("synthetic.op"):
            pass
        obs.histogram("synthetic_seconds", "x").observe(0.01)
        base = f"http://localhost:{server.port}"
        trace = json.load(urllib.request.urlopen(
            base + "/debug/trace"))
        assert any(s["name"] == "synthetic.op"
                   for s in trace["spans"])
        varz = json.load(urllib.request.urlopen(
            base + "/debug/varz"))
        assert varz["tracing_enabled"] is True
        assert "synthetic_seconds" in varz["histograms"]
        perfetto = json.load(urllib.request.urlopen(
            base + "/debug/trace?perfetto=1"))
        assert any(e["name"] == "synthetic.op"
                   for e in perfetto["traceEvents"])
        scrape = urllib.request.urlopen(
            base + "/metrics").read().decode()
        # prometheus_client gauges and the tracer's histograms merge
        # into ONE scrape body.
        assert "tpu_plugin_build_info" in scrape
        assert "synthetic_seconds_bucket" in scrape
        assert "tpu_plugin_metrics_collect_errors_total" in scrape
    finally:
        server.stop()


def test_collect_error_counter_rises(fake_node):
    from container_engine_accelerators_tpu.chip import PyChipBackend
    from container_engine_accelerators_tpu.plugin.manager import (
        TpuManager,
    )
    from container_engine_accelerators_tpu.plugin.metrics import (
        MetricServer,
    )

    fake_node.add_chip(0)
    fake_node.set_topology("1x1")
    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=fake_node.dev_dir,
                     state_dir=fake_node.state_dir, backend=backend)
    mgr.start()
    server = MetricServer(mgr, backend, port=0,
                          pod_resources_socket="/nonexistent")
    server.start()
    try:
        server.collect_once()  # pod-resources socket is unreachable
        scrape = urllib.request.urlopen(
            f"http://localhost:{server.port}/metrics").read().decode()
        assert ("tpu_plugin_metrics_collect_errors_total 1.0"
                in scrape)
    finally:
        server.stop()


# -- gRPC interceptor -------------------------------------------------

def test_allocate_rpc_traced_end_to_end(fake_node):
    from container_engine_accelerators_tpu.chip import PyChipBackend
    from container_engine_accelerators_tpu.plugin import api
    from container_engine_accelerators_tpu.plugin.manager import (
        TpuManager,
    )
    from tests.plugin_helpers import ServingManager, short_tmpdir

    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("1x2")
    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=fake_node.dev_dir,
                     state_dir=fake_node.state_dir, backend=backend)
    mgr.start()
    with ServingManager(mgr, short_tmpdir()) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                container_requests=[
                    api.v1beta1_pb2.ContainerAllocateRequest(
                        devicesIDs=["accel0"])]), timeout=5)
    spans = obs.TRACER.snapshot()["spans"]
    rpc = [s for s in spans if s["name"].endswith("Allocate")]
    assert rpc and rpc[0]["status"] == "ok"
    hists = {(h.name, h.labels.get("method", ""))
             for h in obs.TRACER.histograms()}
    assert any(n == "tpu_plugin_rpc_latency_seconds"
               and m.endswith("Allocate") for n, m in hists)
    events = obs.TRACER.snapshot()["events"]
    alloc = [e for e in events if e["name"] == "allocate.decision"]
    assert alloc and alloc[0]["fields"]["devices"] == ["accel0"]


# -- serving span tree ------------------------------------------------

@pytest.fixture(scope="module")
def predict_server():
    import numpy as np

    from container_engine_accelerators_tpu.serving import (
        InferenceServer,
    )

    def apply_fn(variables, images, train):
        # A linear "model" with no params: logits = sums per class.
        import jax.numpy as jnp
        logits = jnp.stack([images.sum(axis=(1, 2)),
                            -images.sum(axis=(1, 2))], axis=-1)
        return logits, {}

    srv = InferenceServer("m", apply_fn, {"params": {}},
                          input_shape=(2, 2), port=0, max_batch=4,
                          max_wait_ms=1)
    srv.start()
    yield srv, np
    srv.stop()


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://localhost:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return json.load(urllib.request.urlopen(req, timeout=30))


def test_serving_request_span_tree_and_stats(predict_server):
    srv, np = predict_server
    obs.TRACER.reset()
    out = _post(srv.port, "/v1/models/m:predict",
                {"instances": [[[1, 2], [3, 4]]]})
    assert out["predictions"][0]["class"] == 0
    snap = obs.TRACER.snapshot()
    spans = {s["name"]: s for s in snap["spans"]}
    assert "serving.request" in spans
    assert "serving.batch" in spans
    # Cross-thread parenting: the batcher's span joins the request's
    # trace even though it ran on the batcher thread.
    assert (spans["serving.batch"]["trace_id"]
            == spans["serving.request"]["trace_id"])
    assert (spans["serving.batch"]["parent_id"]
            == spans["serving.request"]["span_id"])
    assert not snap["open_spans"]
    # /stats keeps its shape, now histogram-backed.
    stats = json.load(urllib.request.urlopen(
        f"http://localhost:{srv.port}/stats"))
    for key in ("requests", "shed", "platform", "devices",
                "p50_ms", "p99_ms"):
        assert key in stats
    assert stats["requests"] >= 1
    assert stats["p50_ms"] is not None
    # The request latency is scrapeable as a Prometheus histogram.
    text = obs.prometheus_text(obs.TRACER)
    assert 'serving_request_latency_seconds_bucket' in text
    assert 'model="m"' in text


def test_serving_debug_trace_endpoint(predict_server):
    srv, np = predict_server
    obs.TRACER.reset()
    _post(srv.port, "/v1/models/m:predict",
          {"instances": [[[1, 1], [1, 1]]]})
    trace = json.load(urllib.request.urlopen(
        f"http://localhost:{srv.port}/debug/trace"))
    assert any(s["name"] == "serving.request"
               for s in trace["spans"])
    varz = json.load(urllib.request.urlopen(
        f"http://localhost:{srv.port}/debug/varz"))
    assert any("serving_request_latency_seconds" in k
               for k in varz["histograms"])


# -- trace_dump tool --------------------------------------------------

def test_trace_dump_from_live_server_and_file(predict_server,
                                              tmp_path):
    import importlib.util
    import sys

    from tests.conftest import REPO_ROOT

    srv, np = predict_server
    obs.TRACER.reset()
    _post(srv.port, "/v1/models/m:predict",
          {"instances": [[[1, 1], [1, 1]]]})

    spec = importlib.util.spec_from_file_location(
        "trace_dump", os.path.join(REPO_ROOT, "tools",
                                   "trace_dump.py"))
    trace_dump = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_dump)

    out = tmp_path / "trace.json"
    rc = trace_dump.main(["--url", f"http://localhost:{srv.port}",
                          "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["traceEvents"]
    assert any(e["name"] == "serving.request"
               for e in doc["traceEvents"])

    # File mode: the CEA_TPU_TRACE_FILE journal shape round-trips.
    journal = tmp_path / "journal.json"
    journal.write_text(json.dumps(obs.TRACER.snapshot()))
    out2 = tmp_path / "trace2.json"
    rc = trace_dump.main(["--file", str(journal), "--out",
                          str(out2)])
    assert rc == 0
    assert json.loads(out2.read_text())["traceEvents"]

    missing = trace_dump.main(["--file", "/nonexistent",
                               "--out", str(out2)])
    assert missing == 1


def _load_trace_dump():
    import importlib.util

    from tests.conftest import REPO_ROOT

    spec = importlib.util.spec_from_file_location(
        "trace_dump", os.path.join(REPO_ROOT, "tools",
                                   "trace_dump.py"))
    trace_dump = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_dump)
    return trace_dump


def test_trace_dump_journal_round_trip(tmp_path):
    """Journal file -> Perfetto conversion preserves the journal's
    content: every span/event converts with µs timestamps, ids in
    args, the journal's OWN pid on the track, and --raw returns the
    byte-identical snapshot."""
    with obs.span("layer.op", device="accel0"):
        obs.event("layer.mark", n=7)
    snapshot = obs.TRACER.snapshot()
    journal = tmp_path / "journal.json"
    journal.write_text(json.dumps(snapshot))
    trace_dump = _load_trace_dump()

    out = tmp_path / "round.json"
    assert trace_dump.main(["--file", str(journal), "--out",
                            str(out)]) == 0
    doc = json.loads(out.read_text())
    complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    span = snapshot["spans"][0]
    assert len(complete) == 1
    assert complete[0]["ts"] == pytest.approx(
        span["start_unix"] * 1e6)
    assert complete[0]["dur"] == pytest.approx(
        span["duration_s"] * 1e6)
    assert complete[0]["args"]["span_id"] == span["span_id"]
    assert complete[0]["pid"] == snapshot["identity"]["pid"]
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert instants[0]["args"] == {"n": 7}
    # --raw: the snapshot comes back unconverted.
    raw_out = tmp_path / "raw.json"
    assert trace_dump.main(["--file", str(journal), "--raw",
                            "--out", str(raw_out)]) == 0
    assert json.loads(raw_out.read_text()) == snapshot


def test_trace_dump_merge_mode(tmp_path):
    """--merge folds several journals into one timeline (distinct
    pids, all spans present); --raw --merge wraps the originals."""
    with obs.span("proc_a.op"):
        pass
    snap_a = obs.TRACER.snapshot()
    obs.TRACER.reset()
    with obs.span("proc_b.op"):
        pass
    snap_b = dict(obs.TRACER.snapshot())
    # Fake a second process: different pid in the identity stamp.
    snap_b["identity"] = dict(snap_b["identity"],
                              pid=snap_b["identity"]["pid"] + 1,
                              role="other")
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(snap_a))
    b.write_text(json.dumps(snap_b))
    trace_dump = _load_trace_dump()

    out = tmp_path / "merged.json"
    assert trace_dump.main(["--merge", str(a), str(b), "--out",
                            str(out)]) == 0
    doc = json.loads(out.read_text())
    spans = {e["name"]: e for e in doc["traceEvents"]
             if e["ph"] == "X"}
    assert set(spans) == {"proc_a.op", "proc_b.op"}
    assert spans["proc_a.op"]["pid"] != spans["proc_b.op"]["pid"]
    raw_out = tmp_path / "merged_raw.json"
    assert trace_dump.main(["--merge", str(a), str(b), "--raw",
                            "--out", str(raw_out)]) == 0
    assert json.loads(raw_out.read_text()) == {
        "journals": [snap_a, snap_b]}
    # Fleet semantics: a dead operand is skipped with a warning and
    # the surviving journals still merge — one crashed engine must
    # not sink a fleet-wide timeline. Only an ALL-dead merge fails.
    assert trace_dump.main(["--merge", str(a), "/nonexistent",
                            "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    partial = {e["name"] for e in doc["traceEvents"]
               if e["ph"] == "X"}
    assert partial == {"proc_a.op"}
    assert trace_dump.main(["--merge", "/nonexistent",
                            "--out", str(out)]) == 1


def test_trace_file_written_at_exit(tmp_path):
    import subprocess
    import sys

    from tests.conftest import REPO_ROOT

    path = tmp_path / "exit_journal.json"
    code = (
        "from container_engine_accelerators_tpu import obs\n"
        "with obs.span('proc.main'):\n"
        "    obs.event('proc.mark', ok=True)\n")
    env = dict(os.environ, CEA_TPU_TRACE_FILE=str(path),
               PYTHONPATH=REPO_ROOT)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=60,
                          cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(path.read_text())
    assert [s["name"] for s in doc["spans"]] == ["proc.main"]
    assert doc["events"][0]["name"] == "proc.mark"


# -- log format satellite ---------------------------------------------

def test_set_verbosity_and_json_log_format(capfd):
    import logging

    from container_engine_accelerators_tpu.utils import (
        log as log_mod,
        set_verbosity,
    )

    logger = log_mod.get_logger("obs-test")
    set_verbosity(3)
    assert logging.getLogger("cea_tpu").level == logging.DEBUG
    set_verbosity(0)
    assert logging.getLogger("cea_tpu").level == logging.INFO
    os.environ["TPU_PLUGIN_LOG_FORMAT"] = "json"
    try:
        set_verbosity(0)
        logger.info("hello %s", "world")
        err = capfd.readouterr().err
        rec = json.loads(err.strip().splitlines()[-1])
        assert rec["message"] == "hello world"
        assert rec["level"] == "INFO"
        assert isinstance(rec["unix"], float)
    finally:
        del os.environ["TPU_PLUGIN_LOG_FORMAT"]
        set_verbosity(0)
