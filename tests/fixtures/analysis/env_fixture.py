# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seeded violations for the env rules (never imported, only
linted). Each trailing ``# EXPECT:`` names the rules that must fire
on exactly that line; the escape lines must stay silent."""

import os

from container_engine_accelerators_tpu.utils import env_number, env_str

# A raw read of a project env var: both the bare-read rule and (the
# name being absent from the ops table) the registry rule fire.
RAW = os.environ.get("CEA_TPU_FIXTURE_UNDOC")  # EXPECT: bare-env-read,env-registry

# Subscript read form.
RAW2 = os.environ["CEA_TPU_FIXTURE_UNDOC2"]  # EXPECT: bare-env-read,env-registry

# Through the blessed helper, but the knob has no docs row.
HELPED = env_str("CEA_TPU_FIXTURE_UNDOC3")  # EXPECT: env-registry

# Name resolved through a module constant.
KNOB_ENV = "CEA_TPU_FIXTURE_UNDOC4"  # EXPECT: env-registry
KNOB = env_number(KNOB_ENV, 1.0)

# Non-project names are out of scope.
FINE = os.environ.get("PATH")

# A documented project knob read through the helper: clean.
TRACE = env_str("CEA_TPU_TRACE", "1")

# Escapes silence both rules.
ESCAPED = os.environ.get("CEA_TPU_FIXTURE_UNDOC")  # lint: disable=bare-env-read,env-registry

# Writes are harness setup, not reads.
os.environ["CEA_TPU_FIXTURE_UNDOC5"] = "1"  # EXPECT: env-registry
