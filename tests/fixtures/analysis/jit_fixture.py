# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seeded violations for time-in-jit (linted, never imported)."""

import functools
import time

import jax


@jax.jit
def bad_plain(x):
    t0 = time.time()  # EXPECT: time-in-jit
    return x + t0


@functools.partial(jax.jit, static_argnames=("flag",))
def bad_partial(x, flag=True):
    return x * time.perf_counter()  # EXPECT: time-in-jit


@jax.jit
def escaped(x):
    return x + time.monotonic()  # lint: disable=time-in-jit


def timing_outside_is_fine():
    t0 = time.perf_counter()
    return t0
