# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seeded violations for the metric-registry rule (linted, never
imported)."""

# Unregistered metric literal: the drift the rule exists to kill.
DRIFTED = "tpu_fixture_unregistered_series"  # EXPECT: metric-registry

# A typo'd copy of a real name is exactly the same failure mode.
TYPO = "tpu_serving_slot_occupancy_seconds"  # EXPECT: metric-registry

# Registered names, exposition variants, and registered non-metric
# tokens are all clean.
OK = "tpu_train_mfu"
OK_TOTAL = "tpu_plugin_metrics_collect_errors_total"
OK_BUCKET = "tpu_serving_ttft_seconds_bucket"
OK_LABEL = "tpu_device"

# Escape hatch.
ESCAPED = "tpu_fixture_escaped_series"  # lint: disable=metric-registry
