# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seeded violation for jax-free-import: this module declares itself
jax-free (the marker below) and then imports jax at module scope.
Linted, never imported."""

# lint: jax-free

import os  # clean: stdlib

import jax  # EXPECT: jax-free-import


def lazy_is_fine():
    import jax.numpy as jnp  # function-scope: the sanctioned pattern

    return jnp, jax, os
