# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seeded violations for the ledger-writer rule (linted, never
imported)."""

import json
import os

LEDGER = "PERF_LEDGER.json"


def _direct_literal_write():
    # The bypass the rule exists for: rows landed here skip schema
    # validation, the rig fingerprint, and the journal event.
    with open("PERF_LEDGER.json", "a") as f:  # EXPECT: ledger-writer
        f.write("{}\n")


def _resolved_name_write(rows):
    with open(LEDGER, "w") as f:  # EXPECT: ledger-writer
        json.dump(rows, f)


def _joined_path_write(root, rows):
    path = os.path.join(root, "PERF_LEDGER.json")
    del path
    with open(  # EXPECT: ledger-writer
            os.path.join(root, "PERF_LEDGER.json"), mode="w") as f:
        json.dump(rows, f)


def _staged_rename(tmp):
    # Sliding a staged file onto the ledger is the same bypass.
    os.replace(tmp, LEDGER)  # EXPECT: ledger-writer


def _read_only_is_legal():
    # Reports and checks read freely; only writes need the seam.
    with open("PERF_LEDGER.json") as f:
        return json.load(f)


def _escaped_write():
    with open(LEDGER, "w") as f:  # lint: disable=ledger-writer
        f.write("{}\n")
