# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seeded violations for program-registry (linted, never imported).

# lint: program-module
"""

import functools

import jax


@jax.jit  # EXPECT: program-registry
def unregistered_step(x):
    return x + 1


@functools.partial(jax.jit, donate_argnums=(0,))  # EXPECT: program-registry
def unregistered_partial_step(cache):
    return cache * 2


@jax.jit  # lint: disable=program-registry
def escaped_step(x):
    # Deliberately out of the manifest, with the escape saying so.
    return x - 1


@jax.jit
def registered_step(x):
    return x * 3


unregistered_binding = jax.jit(lambda x: x)  # EXPECT: program-registry


def hot_program_specs():
    """The module's registry: referencing registered_step here is
    exactly what keeps it out of the findings."""
    return (registered_step,)
