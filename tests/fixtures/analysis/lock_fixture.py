# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seeded violations for lock-with (linted, never imported)."""

import threading

_LOCK = threading.Lock()


def bare_blocking_acquire():
    _LOCK.acquire()  # EXPECT: lock-with
    try:
        return 1
    finally:
        _LOCK.release()


def checked_probe_is_fine():
    # Non-blocking probe with a checked result: the profiler pattern.
    if _LOCK.acquire(blocking=False):
        try:
            return 1
        finally:
            _LOCK.release()
    return 0


def with_is_fine():
    with _LOCK:
        return 2


def escaped():
    _LOCK.acquire()  # lint: disable=lock-with
    _LOCK.release()
