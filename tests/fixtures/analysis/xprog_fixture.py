# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seeded IR violations for analysis.xprog (imported, then lowered).

Unlike the lint fixtures (linted, never imported), these programs are
really traced: ``fixture_specs()`` hands each one to the IR analyzer
with example args, and every EXPECT annotation must fire at its
decorator line — verified by ``xprog.verify_fixtures`` from both
tests/test_xprog.py and `make analysis-check`. ``clean_specs()`` is
the manifest update-workflow test's tiny registry (no violations).
"""

import functools

import jax
import jax.numpy as jnp

from container_engine_accelerators_tpu.analysis.xprog import HotProgram

# 128 KiB baked into every executable that closes over it — the
# const-capture seed (well above the 4 KiB threshold).
_BIG_TABLE = jnp.zeros((32768,), jnp.float32)


@jax.jit  # EXPECT: donation-miss
def undonated_cache_step(cache, tok):
    # cache is 64*16*4 = 4096 bytes, updated in place shape-to-shape
    # and returned — the classic dropped-donate_argnums double-buffer.
    return cache.at[:, 0].set(tok.astype(cache.dtype)), tok + 1


@jax.jit  # EXPECT: host-callback-in-hot-path
def callback_step(cache, tok):
    jax.debug.print("step tok {t}", t=tok)
    return jnp.sum(cache) + tok.astype(cache.dtype)


@jax.jit  # EXPECT: weak-type-leak
def weak_arg_step(x, alpha):
    # alpha arrives as a host Python float (see fixture_specs): its
    # aval is weakly typed, and the first caller passing a strong
    # jnp scalar recompiles the program.
    return x * alpha


@jax.jit  # EXPECT: const-capture
def const_capture_step(x):
    return x + _BIG_TABLE[: x.shape[0]]


@jax.jit  # EXPECT: dtype-upcast
def upcast_step(x):
    # Declared bfloat16 (see the spec) with an f32 excursion.
    return (x.astype(jnp.float32) * 2.0).astype(jnp.bfloat16)


@functools.partial(jax.jit, donate_argnums=(0,))
def clean_step(cache, tok):
    """The well-behaved control: donates its cache, captures nothing,
    calls nothing back, stays strongly typed."""
    return cache.at[:, 0].set(tok.astype(cache.dtype)), tok + 1


def _cache():
    return jnp.zeros((64, 16), jnp.float32)


def _tok():
    return jnp.asarray(3, jnp.int32)


def fixture_specs():
    """Every seeded violation, one spec per program."""
    return (
        HotProgram("fixture.undonated", undonated_cache_step,
                   (_cache(), _tok())),
        HotProgram("fixture.callback", callback_step,
                   (_cache(), _tok())),
        HotProgram("fixture.weak", weak_arg_step,
                   (jnp.zeros((8,), jnp.float32), 0.5)),
        HotProgram("fixture.const", const_capture_step,
                   (jnp.zeros((8,), jnp.float32),)),
        HotProgram("fixture.upcast", upcast_step,
                   (jnp.zeros((8,), jnp.bfloat16),),
                   compute_dtype="bfloat16"),
    )


def clean_specs():
    """A violation-free mini-registry for manifest round-trip tests."""
    return (
        HotProgram("fixture.clean_step", clean_step,
                   (_cache(), _tok())),
    )
