# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Tiered paged KV: quantized int8/int4 arenas + host-RAM spill tier.

The tier stack's correctness contract extends the paged pool's
(test_paging.py): greedy streams through a QUANTIZED arena are
token-identical to the matching quantized DENSE fallback (same
quantization both sides — paging must add nothing), int4 stays within
the deflaked echo-logprob tolerance of full precision, and the spill
tier's evict -> rehydrate round trip is invisible to streams,
refcounts, reservations, and COW isolation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import TransformerLM
from container_engine_accelerators_tpu.models.decode import (
    SlotDecodeEngine,
    decode,
    greedy_decode,
    kv_token_bytes,
)


def _make_lm(**kw):
    kwargs = dict(vocab_size=48, embed_dim=32, num_layers=2,
                  num_heads=4, max_seq_len=32, dtype=jnp.float32)
    kwargs.update(kw)
    model = TransformerLM(**kwargs)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def lm():
    return _make_lm()


def _pool_is_clean(eng):
    """Refcount exactness (test_paging's invariant): every non-pinned
    block free, nothing shared, no outstanding reservation, tables
    all-trash. The spill tier must never perturb it — host entries
    hold COPIES, not references."""
    pool = eng._pool
    pinned = set(eng._pinned)
    return (pool.free_count() == pool.usable - len(pinned)
            and pool.shared_count() == 0
            and pool.committed == 0
            and bool((eng._tables == eng._trash).all())
            and int(np.abs(pool.ref).sum()) == len(pinned))


def _run_to(eng, prompt, plen, n, **admit_kw):
    """Admit, decode n tokens total (first included), release.
    Returns the token list."""
    slot, first, _, _ = eng.admit(prompt, plen, **admit_kw)
    out = [first]
    for _ in range(n - 1):
        toks, _ = eng.step()
        out.append(int(toks[slot]))
    eng.release(slot)
    return out


def test_int8_paged_token_identical_to_int8_dense(lm):
    """Greedy decode through an int8 paged arena is token-identical
    to the int8 DENSE fallback (kv_quant clones the same cache dtype
    into both pools, so paging adds nothing to the quantization) —
    and both match per-request decode on the int8-cache clone. The
    byte-budget sizing hands the int8 arena ~2x+ the bf16 block
    count at equal HBM."""
    model, params = lm
    prompt = np.array([5, 6, 7, 8, 9, 10], np.int32)
    paged = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                             paged=True, kv_block_size=4,
                             kv_quant="int8")
    dense = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                             paged=False, kv_quant="int8")
    o_p = _run_to(paged, prompt, 6, 6)
    o_d = _run_to(dense, prompt, 6, 6)
    assert o_p == o_d
    ref = np.asarray(greedy_decode(
        model.clone(kv_cache_dtype="int8"), params,
        jnp.asarray(prompt[None]), 6))[0]
    assert o_p == ref[6:12].tolist()
    # Equal-HBM sizing: the quantized arena's resident bytes stay at
    # (or under) the native budget while holding ~2x+ the blocks.
    bf16 = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                            paged=True, kv_block_size=4)
    assert paged._pool.usable >= 2 * bf16._pool.usable
    assert paged.kv_arena_bytes <= bf16.kv_arena_bytes
    stats = paged.kv_block_stats()
    assert stats["kv_quant_mode"] == "int8"
    assert stats["kv_arena_bytes"] == paged.kv_arena_bytes
    assert paged.block_pool_state()["kv_quant_mode"] == "int8"
    assert _pool_is_clean(paged)


def test_int4_paged_matches_dense_and_fp_tolerance(lm):
    """int4: the paged stream is token-identical to STEPWISE-prefill
    decode on the int4 clone (the paged admission chunk attends the
    quantized cache exactly like stepwise does), the dense fallback
    is token-identical to fast-prefill decode (both attend the raw
    prompt chunk), the byte-budget sizing hands ~3x+ the bf16 block
    count, and int4 echo logprobs agree with full precision within
    the deflaked teacher-forced tolerance (PR 6: atol 0.05; int4
    observed ~0.045)."""
    model, params = lm
    prompt = np.array([2, 4, 6, 8, 10, 12], np.int32)
    m4 = model.clone(kv_cache_dtype="int4")
    paged = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                             paged=True, kv_block_size=4,
                             kv_quant="int4")
    dense = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                             paged=False, kv_quant="int4")
    o_p = _run_to(paged, prompt, 6, 6)
    o_d = _run_to(dense, prompt, 6, 6)
    ref_step = np.asarray(decode(
        m4, params, jnp.asarray(prompt[None]), 6,
        fast_prefill=False))[0]
    assert o_p == ref_step[6:12].tolist()
    ref_fast = np.asarray(greedy_decode(
        m4, params, jnp.asarray(prompt[None]), 6))[0]
    assert o_d == ref_fast[6:12].tolist()
    bf16 = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                            paged=True, kv_block_size=4)
    assert paged._pool.usable >= 3 * bf16._pool.usable
    assert paged.kv_arena_bytes <= bf16.kv_arena_bytes
    assert paged.kv_block_stats()["kv_quant_mode"] == "int4"
    # Teacher-forced agreement (the PR 6 deflake method): the paged
    # echo must equal the SAME quantized-cache conditioning computed
    # stepwise (scheduling adds nothing), and sit within the
    # int4-scaled tolerance of full precision — 7-level symmetric
    # quantization observes ~0.19 max echo-logprob delta on this
    # model (int8's was ~0.009 against its 0.05 bound; int4 carries
    # 4 fewer bits, so the bound scales to 0.25).
    echo4 = paged.score(prompt, 6)
    _, lps4 = decode(m4, params, jnp.asarray(prompt[None]), 1,
                     fast_prefill=False, return_logprobs=True)
    np.testing.assert_allclose(echo4[:6], np.asarray(lps4)[0][:6],
                               atol=1e-4)
    _, lps = decode(model, params, jnp.asarray(prompt[None]), 1,
                    return_logprobs=True)
    np.testing.assert_allclose(echo4[:6], np.asarray(lps)[0][:6],
                               atol=0.25)
    assert _pool_is_clean(paged)


def test_spill_rehydrate_stream_bitexact_and_refcounts_exact(lm):
    """Cold registered blocks evict to the host tier at reuse and
    rehydrate on a content-key hit: the re-admitted stream is
    token-identical to per-request decode (the round trip is byte-
    preserving), refcounts/reservations return to exactly clean, and
    turning spill OFF makes the same traffic re-prefill instead (no
    hits, same stream)."""
    model, params = lm
    A = np.array([1, 2, 3, 4, 5, 6], np.int32)
    fillers = (np.array([9, 8, 7, 6, 5, 4], np.int32),
               np.array([11, 12, 13, 14, 15, 16], np.int32))
    ref = np.asarray(greedy_decode(
        model, params, jnp.asarray(A[None]), 4))[0][6:10].tolist()
    for spill in (True, False):
        # One row's worth of blocks: every admission recycles the
        # previous row's registered blocks.
        eng = SlotDecodeEngine(model, params, slots=1, slot_len=12,
                               paged=True, kv_block_size=4,
                               kv_blocks=4, kv_spill=spill)
        oa = _run_to(eng, A, 6, 4, max_new=4)
        for f in fillers:
            _run_to(eng, f, 6, 4, max_new=4)
        oa2 = _run_to(eng, A, 6, 4, max_new=4)
        assert oa == ref and oa2 == ref
        stats = eng.kv_block_stats()
        if spill:
            assert stats["kv_spill_hits"] >= 1
            assert stats["kv_rehydrated_blocks"] >= 1
            assert stats["kv_spill_blocks"] >= 1
            assert eng.drain_rehydrate_events()
            assert eng.drain_rehydrate_events() == []
        else:
            assert stats["kv_spill_hits"] == 0
            assert stats["kv_spill_blocks"] == 0
        assert _pool_is_clean(eng)


def test_cow_isolation_across_evict_rehydrate_fork(lm):
    """COW isolation survives the spill round trip: a prefix whose
    partial boundary block was evicted to the host tier and
    rehydrated forks exactly like a resident one — the rehydrated
    donor and a row forked from it decode independently to their own
    per-request references, and a LATER fork taken directly from the
    host tier (hydrate-into-destination, no resident donor) is exact
    too."""
    model, params = lm
    shared = np.array([3, 1, 4, 1, 5, 9], np.int32)          # 1 full + 2
    sa = np.concatenate([shared, [11]]).astype(np.int32)     # plen 7
    sb = np.concatenate([shared, [17]]).astype(np.int32)
    sc = np.concatenate([shared, [29]]).astype(np.int32)
    fillers = (np.array([40, 41, 42, 43, 44, 45, 46], np.int32),
               np.array([30, 31, 32, 33, 34, 35, 36], np.int32))
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                           paged=True, kv_block_size=4, kv_blocks=9,
                           kv_spill=True)
    # Seed the tier: admit/release the donor, then churn enough
    # fillers that its blocks are recycled (spilled).
    _run_to(eng, sa, 7, 3, max_new=3)
    for f in fillers:
        _run_to(eng, f, 7, 3, max_new=3)
    assert eng.kv_block_stats()["kv_spill_blocks"] >= 1
    # Rehydrate the donor and fork a second row off the rehydrated
    # partial block while the donor keeps writing into it.
    slot_a, fa, _, _ = eng.admit(sa, 7, max_new=6)
    oa = [fa]
    toks, _ = eng.step()
    oa.append(int(toks[slot_a]))
    slot_b, fb, _, _ = eng.admit(sb, 7, max_new=5)
    ob = [fb]
    for _ in range(4):
        toks, _ = eng.step()
        oa.append(int(toks[slot_a]))
        ob.append(int(toks[slot_b]))
    ref = np.asarray(greedy_decode(
        model, params, jnp.asarray(np.stack([sa, sb])), 6))
    assert oa == ref[0, 7:13].tolist()
    assert ob == ref[1, 7:12].tolist()
    eng.release(slot_a)
    eng.release(slot_b)
    # Recycle again, then fork DIRECTLY from the host tier.
    for f in fillers:
        _run_to(eng, f, 7, 3, max_new=3)
    oc = _run_to(eng, sc, 7, 5, max_new=5)
    ref_c = np.asarray(greedy_decode(
        model, params, jnp.asarray(sc[None]), 5))[0]
    assert oc == ref_c[7:12].tolist()
    assert _pool_is_clean(eng)


def test_exhaustion_with_full_spill_tier_queues_cleanly(lm):
    """Block exhaustion with a saturated (byte-starved, constantly
    evicting) spill tier still QUEUES admissions: can_admit False,
    admit raises, the resident row's table/stream stay intact, and
    the queued admission lands exactly after a release."""
    model, params = lm
    # Spill budget below one block's bytes: every capture is
    # immediately evicted — the tier is permanently "full".
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=12,
                           paged=True, kv_block_size=4, kv_blocks=4,
                           kv_spill=True, kv_spill_bytes=64)
    pa = np.array([1, 2, 3, 4], np.int32)
    pb = np.array([9, 8, 7, 6], np.int32)
    _run_to(eng, pb, 4, 3, max_new=4)    # registers, then recycles
    slot_a, fa, _, _ = eng.admit(pa, 4, max_new=8)
    assert not eng.can_admit(pb, 4, 8)
    with pytest.raises(RuntimeError, match="KV block"):
        eng.admit(pb, 4, max_new=8)
    oa = [fa]
    for _ in range(5):
        toks, _ = eng.step()
        oa.append(int(toks[slot_a]))
    ref_a = np.asarray(greedy_decode(
        model, params, jnp.asarray(pa[None]), 6))[0]
    assert oa == ref_a[4:10].tolist()
    # pa's block-boundary growth recycled pb's registered blocks —
    # captures happened, but the 64-byte budget evicted them at
    # once: the tier is permanently "full" and stays empty.
    assert eng._pool.spill_captures >= 1
    assert eng._pool.spill_evictions >= 1
    assert eng.kv_block_stats()["kv_spill_blocks"] == 0
    eng.release(slot_a)
    assert eng.can_admit(pb, 4, 8)
    ob = _run_to(eng, pb, 4, 6, max_new=8)
    ref_b = np.asarray(greedy_decode(
        model, params, jnp.asarray(pb[None]), 6))[0]
    assert ob == ref_b[4:10].tolist()
    assert _pool_is_clean(eng)


def test_failed_admission_rolls_back_pool_state(lm, monkeypatch):
    """A device-side failure mid-admission (hydrate/prefill/insert
    raising) leaves the pool EXACTLY as it found it — no leaked
    refs or allocations, tables all-trash, no stale slot_blocks —
    because the serving loop catches admission errors and keeps
    serving; the next admission of the same prompt must succeed and
    stream exactly."""
    from container_engine_accelerators_tpu.models import (
        decode as decode_mod,
    )
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=12,
                           paged=True, kv_block_size=4, kv_blocks=7,
                           kv_spill=True)
    A = np.array([1, 2, 3, 4, 5, 6], np.int32)
    _run_to(eng, A, 6, 3, max_new=3)        # registers the prefix
    real = decode_mod._paged_prefill_impl

    def boom(*args, **kwargs):
        raise RuntimeError("synthetic device failure")

    monkeypatch.setattr(decode_mod, "_paged_prefill_impl", boom)
    with pytest.raises(RuntimeError, match="synthetic"):
        eng.admit(A, 6, max_new=3)          # revival + fork path
    monkeypatch.setattr(decode_mod, "_paged_prefill_impl", real)
    assert _pool_is_clean(eng)
    assert eng.free_slots() == 2
    out = _run_to(eng, A, 6, 4, max_new=4)
    ref = np.asarray(greedy_decode(
        model, params, jnp.asarray(A[None]), 4))[0]
    assert out == ref[6:10].tolist()
    assert _pool_is_clean(eng)


def test_spill_tier_lru_evicts_at_byte_budget(lm):
    """The host tier is BOUNDED: a budget sized for roughly one
    prefix's blocks keeps the LRU at/below it as distinct prefixes
    churn through, and an evicted prefix is a true miss (re-prefill,
    still exact)."""
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=1, slot_len=12,
                           paged=True, kv_block_size=4, kv_blocks=4,
                           kv_spill=True)
    # Derive one block's spill payload bytes from a first capture.
    A = np.array([1, 2, 3, 4, 5, 6], np.int32)
    B = np.array([9, 8, 7, 6, 5, 4], np.int32)
    C = np.array([11, 12, 13, 14, 15, 16], np.int32)
    _run_to(eng, A, 6, 3, max_new=3)
    _run_to(eng, B, 6, 3, max_new=3)     # spills A's blocks
    pool = eng._pool
    assert pool.spill_bytes_used > 0
    per_block = pool.spill_bytes_used // pool.spill_block_count()
    # Rebuild with a budget of ~2 blocks: the 2-block prompts churn
    # the tier and the LRU must hold the line.
    eng = SlotDecodeEngine(model, params, slots=1, slot_len=12,
                           paged=True, kv_block_size=4, kv_blocks=4,
                           kv_spill=True,
                           kv_spill_bytes=int(2 * per_block))
    for row in (A, B, C, A, B, C):
        out = _run_to(eng, row, 6, 4, max_new=4)
        ref = np.asarray(greedy_decode(
            model, params, jnp.asarray(row[None]), 4))[0]
        assert out == ref[6:10].tolist()
        assert eng._pool.spill_bytes_used <= int(2 * per_block)
    assert eng._pool.spill_evictions >= 1
    assert _pool_is_clean(eng)
