# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Metrics-server tests: pod-resources stub -> gauges -> HTTP scrape.

The reference's metrics package is untested (needs NVML + kubelet,
SURVEY.md section 4); here both seams are faked: a PodResourcesLister
stub on a unix socket and the chip backend's state files.
"""

import os
import urllib.request
from concurrent import futures

import grpc
import pytest

from container_engine_accelerators_tpu.chip import PyChipBackend
from container_engine_accelerators_tpu.plugin import api
from container_engine_accelerators_tpu.plugin.api.grpc_bindings import (
    PodResourcesListerServicer,
    add_pod_resources_lister,
)
from container_engine_accelerators_tpu.plugin.devices import (
    get_devices_for_all_containers,
)
from container_engine_accelerators_tpu.plugin.manager import TpuManager
from container_engine_accelerators_tpu.plugin.metrics import MetricServer
from tests.plugin_helpers import short_tmpdir


class PodResourcesStub(PodResourcesListerServicer):
    """Fake kubelet pod-resources endpoint."""

    def __init__(self, socket_path, payload):
        self._payload = payload
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        add_pod_resources_lister(self, self._server)
        self._server.add_insecure_port(f"unix://{socket_path}")

    def List(self, request, context):
        return self._payload

    def set_payload(self, payload):
        """Swap the advertised pod set (container churn simulation)."""
        self._payload = payload

    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop(grace=0)


def payload_two_pods():
    return api.podresources_pb2.ListPodResourcesResponse(pod_resources=[
        api.podresources_pb2.PodResources(
            name="train-0", namespace="default", containers=[
                api.podresources_pb2.ContainerResources(
                    name="jax", devices=[
                        api.podresources_pb2.ContainerDevices(
                            resource_name="google.com/tpu",
                            device_ids=["accel0", "accel1"])])]),
        api.podresources_pb2.PodResources(
            name="other", namespace="default", containers=[
                api.podresources_pb2.ContainerResources(
                    name="app", devices=[
                        api.podresources_pb2.ContainerDevices(
                            resource_name="nvidia.com/gpu",
                            device_ids=["nvidia0"])])]),
    ])


@pytest.fixture
def node2(fake_node):
    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("1x2")
    return fake_node


def test_pod_resources_client_filters_resource(node2):
    sock = os.path.join(short_tmpdir(), "podres.sock")
    stub = PodResourcesStub(sock, payload_two_pods())
    stub.start()
    try:
        out = get_devices_for_all_containers(sock)
        assert len(out) == 1
        assert out[0].pod == "train-0"
        assert out[0].device_ids == ["accel0", "accel1"]
    finally:
        stub.stop()


def test_collect_and_scrape(node2):
    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=node2.dev_dir, state_dir=node2.state_dir,
                     backend=backend)
    mgr.start()
    node2.set_state(0, "hbm", "17179869184 4096")
    node2.set_state(1, "hbm", "17179869184 8192")
    node2.set_state(0, "duty_cycle", "0 0")
    node2.set_state(1, "duty_cycle", "0 0")

    sock = os.path.join(short_tmpdir(), "podres.sock")
    stub = PodResourcesStub(sock, payload_two_pods())
    stub.start()
    server = MetricServer(mgr, backend, port=0,
                          pod_resources_socket=sock)
    server.start()
    try:
        server.collect_once()
        # Advance the duty counters 60% busy and collect again so the
        # windowed average has two samples.
        node2.set_state(0, "duty_cycle", "600000 1000000")
        node2.set_state(1, "duty_cycle", "300000 1000000")
        server.collect_once()
        body = urllib.request.urlopen(
            f"http://localhost:{server.port}/metrics").read().decode()
        assert ('duty_cycle{container="jax",namespace="default",'
                'pod="train-0",tpu_device="accel0"} 60.0') in body
        assert ('memory_used{container="jax",namespace="default",'
                'pod="train-0",tpu_device="accel1"} 8192.0') in body
        assert ('request_count{container="jax",namespace="default",'
                'pod="train-0"} 2.0') in body
        assert 'device_healthy{tpu_device="accel0"} 1.0' in body
        assert "nvidia0" not in body
        # The gauge tracks the manager's health gate.
        from container_engine_accelerators_tpu.plugin.api import (
            UNHEALTHY,
        )
        mgr.set_device_health("accel1", UNHEALTHY)
        server.collect_once()
        body = urllib.request.urlopen(
            f"http://localhost:{server.port}/metrics").read().decode()
        assert 'device_healthy{tpu_device="accel1"} 0.0' in body
        # Wrong path 404s (the reference serves only metricsPath).
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://localhost:{server.port}/other")
    finally:
        server.stop()
        stub.stop()


def test_reset_drops_stale_labels(node2):
    from container_engine_accelerators_tpu import obs
    from container_engine_accelerators_tpu.plugin import placement

    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=node2.dev_dir, state_dir=node2.state_dir,
                     backend=backend)
    mgr.start()
    sock = os.path.join(short_tmpdir(), "podres.sock")
    stub = PodResourcesStub(sock, payload_two_pods())
    stub.start()
    server = MetricServer(mgr, backend, port=0, pod_resources_socket=sock)
    server.start()
    try:
        server.collect_once()
        # The placement gauges ride the same reset cycle — a series
        # under a stale shape label (what a repartition leaves
        # behind) drops; the current shape's series ("none" on this
        # un-partitioned node) survives so the scrape never blinks
        # between policy passes.
        obs.gauge(placement.FRAGMENTATION_GAUGE, 0.5, shape="4x1")
        obs.gauge(placement.FRAGMENTATION_GAUGE, 0.0, shape="none")
        obs.gauge(placement.PLACEMENT_SCORE_GAUGE, 1.25, shape="4x1")
        body = urllib.request.urlopen(
            f"http://localhost:{server.port}/metrics").read().decode()
        assert 'pod="train-0"' in body
        assert 'tpu_plugin_fragmentation{shape="4x1"} 0.5' in body
        server._reset()
        body = urllib.request.urlopen(
            f"http://localhost:{server.port}/metrics").read().decode()
        assert 'pod="train-0"' not in body
        assert 'shape="4x1"' not in body
        assert 'tpu_plugin_fragmentation{shape="none"} 0.0' in body
    finally:
        server.stop()
        stub.stop()


def test_collect_feeds_placement_profiles(node2):
    """The metrics ticker is the MISO learning loop: per-container
    duty/HBM samples land in the manager's ProfileStore keyed
    namespace/container."""
    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=node2.dev_dir, state_dir=node2.state_dir,
                     backend=backend)
    mgr.start()
    node2.set_state(0, "hbm", "1000 400")
    node2.set_state(1, "hbm", "1000 800")
    node2.set_state(0, "duty_cycle", "0 0")
    node2.set_state(1, "duty_cycle", "0 0")
    sock = os.path.join(short_tmpdir(), "podres.sock")
    stub = PodResourcesStub(sock, payload_two_pods())
    stub.start()
    server = MetricServer(mgr, backend, port=0,
                          pod_resources_socket=sock)
    try:
        server.collect_once()
        node2.set_state(0, "duty_cycle", "600000 1000000")
        node2.set_state(1, "duty_cycle", "300000 1000000")
        server.collect_once()
        demand = mgr.placement_profiles().demand("default/jax")
        # HBM watermark is the binding resource: max(400/1000,
        # 800/1000) = 0.8 beats the mean duty cycle.
        assert demand == pytest.approx(0.8)
        state = mgr.placement_profiles().state()["default/jax"]
        assert 0.0 < state["mfu"] <= 0.6
    finally:
        server.stop()
        stub.stop()


def test_reset_cycle_drops_departed_container_labels(node2,
                                                     monkeypatch):
    """The stale-label RESET cycle end to end, through the real
    collection thread (metrics.go:63,158-167 behavior): label sets
    for a container that DEPARTED keep being served only until the
    next reset tick, after which the scrape carries the live pod set
    only. test_reset_drops_stale_labels covers the _reset() seam;
    this covers the ticker actually firing it."""
    import time

    from container_engine_accelerators_tpu.plugin import (
        metrics as metrics_mod,
    )

    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=node2.dev_dir, state_dir=node2.state_dir,
                     backend=backend)
    mgr.start()
    sock = os.path.join(short_tmpdir(), "podres.sock")
    stub = PodResourcesStub(sock, payload_two_pods())
    stub.start()
    # Fast cycles: collect every 30ms, reset every ~90ms.
    monkeypatch.setattr(metrics_mod, "RESET_INTERVAL_MS", 90)
    server = MetricServer(mgr, backend, collection_interval_ms=30,
                          port=0, pod_resources_socket=sock)
    server.start()

    def scrape():
        return urllib.request.urlopen(
            f"http://localhost:{server.port}/metrics").read().decode()

    def wait_for(predicate, deadline_s=15):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            body = scrape()
            if predicate(body):
                return body
            time.sleep(0.05)
        return scrape()

    try:
        body = wait_for(lambda b: 'pod="train-0"' in b)
        assert 'pod="train-0"' in body
        # The pod departs: the kubelet stops listing it.
        stub.set_payload(
            api.podresources_pb2.ListPodResourcesResponse(
                pod_resources=[api.podresources_pb2.PodResources(
                    name="late-1", namespace="default", containers=[
                        api.podresources_pb2.ContainerResources(
                            name="jax", devices=[
                                api.podresources_pb2.ContainerDevices(
                                    resource_name="google.com/tpu",
                                    device_ids=["accel1"])])])]))
        body = wait_for(lambda b: ('pod="train-0"' not in b
                                   and 'pod="late-1"' in b))
        assert 'pod="train-0"' not in body  # departed: dropped
        assert 'pod="late-1"' in body       # live: re-collected
    finally:
        server.stop()
        stub.stop()


def test_unreachable_pod_resources_is_soft(node2):
    backend = PyChipBackend()
    mgr = TpuManager(dev_dir=node2.dev_dir, state_dir=node2.state_dir,
                     backend=backend)
    mgr.start()
    server = MetricServer(mgr, backend, port=0,
                          pod_resources_socket="/nonexistent/sock")
    server.start()
    try:
        server.collect_once()  # must not raise
    finally:
        server.stop()


def test_telemetry_probe_writes_auditable_record(tmp_path):
    """tools/telemetry_probe.py must always produce a record —
    success or structured failure per source leg — with host
    observations and provenance (VERDICT r3 missing #3: the real
    telemetry legs need a committed outcome, even a documented
    failure). 'ok' requires actual chip readings: a constructible
    SDK that polls zero chips is not a real source."""
    import json
    import subprocess
    import sys

    from tests.conftest import REPO_ROOT

    out = tmp_path / "probe.json"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "telemetry_probe.py"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=110, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(out.read_text())
    assert d["metric"] == "telemetry_source_probe"
    for leg in [d["sdk"]] + list(d["grpc"].values()):
        assert "ok" in leg
        if leg["ok"]:
            assert leg["chips_seen"] > 0
        else:
            assert leg.get("error") or leg.get("error_type")
    assert "candidate_ports" in d["host_observations"]
    assert d["provenance"]["git_sha"]
    # The varz legs snapshot /debug/varz from live obs-instrumented
    # processes; with none running the outcome is a structured
    # failure, never a crash.
    assert d["varz"]
    for leg in d["varz"].values():
        assert "ok" in leg
        if leg["ok"]:
            assert "journal" in leg
        else:
            assert leg.get("error") or leg.get("error_type")
    last = json.loads(proc.stdout.strip().splitlines()[-1])
    assert last["any_real_source"] == d["any_real_source"]
