# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Efficiency accounting: MFU/goodput ledgers, HBM memory telemetry,
on-demand profiler capture, and the serving SLO surface (TTFT/TPOT)
on a real engine-mode server."""

import json
import os
import threading
import urllib.error
import urllib.request

import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.obs import efficiency, memory
from container_engine_accelerators_tpu.obs import (
    postmortem,
    profiler,
)


@pytest.fixture(autouse=True)
def clean_tracer():
    obs.TRACER.reset()
    yield
    obs.TRACER.reset()


# -- peak FLOPs + numerators ------------------------------------------

def test_peak_flops_table_and_override(monkeypatch):
    monkeypatch.delenv(efficiency.PEAK_FLOPS_ENV, raising=False)
    assert efficiency.peak_flops_per_chip("TPU v4") == 275e12
    # Longest-match: "v5 lite" must not resolve through the bare
    # "v5" (v5p-class) entry.
    assert efficiency.peak_flops_per_chip("TPU v5 lite") == 197e12
    assert efficiency.peak_flops_per_chip("TPU v5") == 459e12
    assert efficiency.peak_flops_per_chip("cpu") is None
    assert efficiency.peak_flops_per_chip(None) is None
    monkeypatch.setenv(efficiency.PEAK_FLOPS_ENV, "123.5e12")
    assert efficiency.peak_flops_per_chip("cpu") == 123.5e12
    monkeypatch.setenv(efficiency.PEAK_FLOPS_ENV, "junk")
    assert efficiency.peak_flops_per_chip("TPU v4") == 275e12


def test_flops_from_cost_analysis_shapes():
    f = efficiency.flops_from_cost_analysis
    assert f(None) is None
    assert f({"bytes accessed": 5.0}) is None
    assert f({"flops": 1024.0}) == 1024.0
    assert f([{"flops": 10.0}, {"flops": 5.0}]) == 15.0
    assert f([{"other": 1}]) is None
    assert f("not a dict") is None


def test_analytic_flops_formulas():
    assert efficiency.transformer_train_flops(100, 32) == 6 * 100 * 32
    assert efficiency.transformer_decode_flops(100, 4) == 2 * 100 * 4


def test_flops_ledger_publishes_gauge():
    ledger = efficiency.FlopsLedger(
        gauge="test_mfu", peak_flops=1000.0, chips=2,
        publish_every=4)
    # First observation publishes; achieved = 100/0.1 = 1000 FLOP/s
    # over peak 1000*2 -> 0.5.
    ledger.observe(100.0, 0.1)
    assert ledger.mfu() == pytest.approx(0.5)
    gauges = {n: v for (n, _), v in obs.TRACER.gauges().items()}
    assert gauges["test_mfu"] == pytest.approx(0.5)
    assert ledger.achieved_flops() == pytest.approx(1000.0)
    # No peak -> no gauge, but achieved FLOP/s still tracked.
    obs.TRACER.reset()
    nop = efficiency.FlopsLedger(gauge="test_mfu2", peak_flops=None)
    nop.observe(100.0, 0.1)
    assert nop.mfu() is None
    assert nop.achieved_flops() == pytest.approx(1000.0)
    assert not obs.TRACER.gauges()
    # Zero/None observations are ignored, never a divide.
    ledger.observe(None, 0.1)
    ledger.observe(100.0, 0.0)


# -- goodput ledger ---------------------------------------------------

def test_goodput_ledger_live_books_balance():
    t = [0.0]
    ledger = efficiency.GoodputLedger(clock=lambda: t[0])
    ledger.record("compile", 2.0)
    ledger.record("productive", 5.0)
    ledger.record("data_wait", 1.0)
    t[0] = 10.0
    s = ledger.summary()
    assert s["wall_s"] == 10.0
    assert s["goodput_ratio"] == pytest.approx(0.5)
    assert s["buckets"]["other"] == pytest.approx(2.0)
    assert sum(s["buckets"].values()) == pytest.approx(10.0)
    out = ledger.publish()
    gauges = {(n, labels): v
              for (n, labels), v in obs.TRACER.gauges().items()}
    assert gauges[(efficiency.GOODPUT_GAUGE, ())] \
        == pytest.approx(0.5)
    assert gauges[(efficiency.BADPUT_GAUGE,
                   (("bucket", "compile"),))] == pytest.approx(2.0)
    assert out == s


def test_goodput_ledger_overlap_rescales_to_wall():
    """Overlapping attributions (async checkpoint under compute) can
    exceed wall; the books rescale rather than report >100%."""
    t = [0.0]
    ledger = efficiency.GoodputLedger(clock=lambda: t[0])
    ledger.record("productive", 6.0)
    ledger.record("checkpoint", 2.0)
    t[0] = 4.0
    s = ledger.summary()
    assert s["wall_s"] == 4.0
    assert sum(s["buckets"].values()) == pytest.approx(4.0)
    assert s["buckets"]["productive"] == pytest.approx(3.0)
    assert s["buckets"]["checkpoint"] == pytest.approx(1.0)


def test_goodput_ledger_rejects_unknown_bucket():
    with pytest.raises(ValueError, match="unknown goodput bucket"):
        efficiency.GoodputLedger().record("coffee", 1.0)


def test_replay_known_timings_sum_to_wall():
    t0 = 500.0
    snapshot = {
        "identity": {"role": "train", "host": "h0", "pid": 7},
        "spans": [
            {"name": "train.step_compile", "start_unix": t0,
             "duration_s": 1.0},
            {"name": "train.step_run", "start_unix": t0 + 1.0,
             "duration_s": 2.0},
            {"name": "train.data_wait", "start_unix": t0 + 3.0,
             "duration_s": 0.5},
            {"name": "train.checkpoint", "start_unix": t0 + 3.5,
             "duration_s": 0.5},
            {"name": "unrelated.span", "start_unix": t0 + 4.0,
             "duration_s": 1.0},  # -> other
        ],
        "events": [{"name": "train.restart", "unix": t0,
                    "fields": {"recovery_s": 0.25}}],
    }
    s = efficiency.ledger_from_snapshot(snapshot).summary()
    assert s["wall_s"] == pytest.approx(5.0)
    b = s["buckets"]
    assert b["compile"] == pytest.approx(1.0)
    assert b["productive"] == pytest.approx(2.0)
    assert b["data_wait"] == pytest.approx(0.5)
    assert b["checkpoint"] == pytest.approx(0.5)
    assert b["restart"] == pytest.approx(0.25)
    assert b["other"] == pytest.approx(0.75)
    assert sum(b.values()) == pytest.approx(s["wall_s"], rel=0.01)
    assert s["goodput_ratio"] == pytest.approx(0.4)


def test_replay_straggler_episode_moves_productive_to_stall():
    """A detected->recovered episode at skew 2.0 converts half the
    episode's span to straggler_stall, deducted from productive."""
    t0 = 100.0
    snapshot = {
        "identity": {"role": "train", "host": "h1", "pid": 8},
        "spans": [
            {"name": "train.step_run", "start_unix": t0,
             "duration_s": 8.0},
        ],
        "events": [
            {"name": "straggler.detected", "unix": t0 + 2.0,
             "fields": {"host": "h1", "skew_ratio": 2.0}},
            {"name": "straggler.recovered", "unix": t0 + 6.0,
             "fields": {"host": "h1"}},
        ],
    }
    s = efficiency.ledger_from_snapshot(snapshot).summary()
    # stall = 4s episode * (1 - 1/2) = 2s
    assert s["buckets"]["straggler_stall"] == pytest.approx(2.0)
    assert s["buckets"]["productive"] == pytest.approx(6.0)
    assert sum(s["buckets"].values()) == pytest.approx(s["wall_s"])


def test_replay_stall_clamped_to_recorded_productive():
    """A dropped-span journal (episode events survive, most step
    spans fell off the ring): stall can only reclassify time the
    journal actually recorded as productive — the books still
    balance and unrecorded time stays in 'other'."""
    t0 = 100.0
    snapshot = {
        "identity": {"role": "train", "host": "h1", "pid": 9},
        "spans": [
            {"name": "train.step_run", "start_unix": t0,
             "duration_s": 1.0},
        ],
        "events": [
            {"name": "straggler.detected", "unix": t0,
             "fields": {"host": "h1", "skew_ratio": 10.0}},
            {"name": "straggler.recovered", "unix": t0 + 100.0,
             "fields": {"host": "h1"}},
        ],
    }
    s = efficiency.ledger_from_snapshot(snapshot).summary()
    # Raw stall would be 90s; only the 1s of recorded productive
    # time can move.
    assert s["buckets"]["straggler_stall"] == pytest.approx(1.0)
    assert s["buckets"]["productive"] == pytest.approx(0.0)
    assert sum(s["buckets"].values()) == pytest.approx(s["wall_s"])


def test_report_combines_processes():
    snap = {
        "identity": {"role": "train", "host": "h", "pid": 1},
        "spans": [{"name": "train.step_run", "start_unix": 0.0,
                   "duration_s": 1.0}],
        "events": [],
    }
    other = dict(snap, identity={"role": "serving", "host": "h",
                                 "pid": 2})
    report = efficiency.report_from_snapshots([snap, other])
    assert len(report["processes"]) == 2
    assert report["processes"][0]["identity"]["role"] == "train"
    combined = report["combined"]
    assert combined["wall_s"] == pytest.approx(2.0)
    assert combined["buckets"]["productive"] == pytest.approx(2.0)
    assert combined["goodput_ratio"] == pytest.approx(1.0)


def test_engine_active_param_count_discounts_unrouted_experts():
    """MoE decode executes only top_k of num_experts expert MLPs per
    token: the MFU numerator's param count must discount the
    unrouted experts (expert-stacked leaves), not the router gate."""
    import jax
    import jax.numpy as jnp

    from container_engine_accelerators_tpu.models import (
        MoETransformerLM,
        TransformerLM,
    )
    from container_engine_accelerators_tpu.models.decode import (
        SlotDecodeEngine,
    )

    dense = TransformerLM(vocab_size=48, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = dense.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    eng = SlotDecodeEngine(dense, params, slots=1, slot_len=14)
    assert eng.active_param_count == eng.param_count

    moe = MoETransformerLM(vocab_size=48, embed_dim=32,
                           num_layers=2, num_heads=4,
                           max_seq_len=32, num_experts=4, top_k=1,
                           dtype=jnp.float32)
    moe_params = moe.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    eng = SlotDecodeEngine(moe, moe_params, slots=1, slot_len=14)
    assert eng.active_param_count < eng.param_count
    # Exactly: expert-stacked leaves (leading dim == num_experts,
    # rank >= 3) count at top_k/num_experts.
    import jax.tree_util as jtu
    expected = sum(
        (int(p.size) * 1 // 4 if p.ndim >= 3 and p.shape[0] == 4
         else int(p.size))
        for p in jtu.tree_leaves(moe_params))
    assert eng.active_param_count == expected


# -- HBM memory telemetry ---------------------------------------------

class _FakeDev:
    def __init__(self, name, in_use, limit, peak=None, stats=True):
        self._name = name
        self._stats = ({"bytes_in_use": in_use,
                        "peak_bytes_in_use": peak or in_use,
                        "bytes_limit": limit} if stats else None)

    def memory_stats(self):
        return self._stats

    def __str__(self):
        return self._name


def test_memory_monitor_gauges_and_watermark():
    mon = memory.MemoryMonitor(soft_limit=0.9)
    stats = mon.sample(devices=[
        _FakeDev("tpu0", 400, 1000, peak=450),
        _FakeDev("cpu0", 0, 0, stats=False),  # no allocator stats
    ])
    assert set(stats) == {"tpu0"}
    gauges = {(n, labels): v
              for (n, labels), v in obs.TRACER.gauges().items()}
    dev = (("device", "tpu0"),)
    assert gauges[(memory.IN_USE_GAUGE, dev)] == 400
    assert gauges[(memory.PEAK_GAUGE, dev)] == 450
    assert gauges[(memory.LIMIT_GAUGE, dev)] == 1000
    # Watermark only ratchets up.
    mon.sample(devices=[_FakeDev("tpu0", 300, 1000)])
    assert mon.watermarks()["tpu0"] == 450
    mon.sample(devices=[_FakeDev("tpu0", 700, 1000)])
    assert mon.watermarks()["tpu0"] == 700
    totals = mon.totals()
    assert totals["hbm_in_use_bytes"] == 700
    assert totals["hbm_peak_bytes"] == 700


def test_memory_pressure_exactly_one_event_per_episode():
    mon = memory.MemoryMonitor(soft_limit=0.9)

    def events():
        return [e for e in obs.TRACER.snapshot()["events"]
                if e["name"] in (memory.PRESSURE_EVENT,
                                 memory.RECOVERED_EVENT)]

    mon.sample(devices=[_FakeDev("tpu0", 950, 1000)])
    mon.sample(devices=[_FakeDev("tpu0", 960, 1000)])  # still in
    assert [e["name"] for e in events()] == [memory.PRESSURE_EVENT]
    assert events()[0]["fields"]["device"] == "tpu0"
    # Above the recovery threshold (0.85): the episode stays open.
    mon.sample(devices=[_FakeDev("tpu0", 870, 1000)])
    assert len(events()) == 1
    # Recovery fires once, re-arming the alarm.
    mon.sample(devices=[_FakeDev("tpu0", 800, 1000)])
    assert [e["name"] for e in events()] == [
        memory.PRESSURE_EVENT, memory.RECOVERED_EVENT]
    mon.sample(devices=[_FakeDev("tpu0", 990, 1000)])
    assert [e["name"] for e in events()] == [
        memory.PRESSURE_EVENT, memory.RECOVERED_EVENT,
        memory.PRESSURE_EVENT]


def test_memory_monitor_throttles_inside_interval():
    mon = memory.MemoryMonitor(soft_limit=0.9)
    mon.sample(devices=[_FakeDev("tpu0", 100, 1000)])
    # Inside the interval the cached sample answers; the new device
    # list is not consulted.
    cached = mon.sample(devices=[_FakeDev("tpu0", 999, 1000)],
                        min_interval_s=60.0)
    assert cached["tpu0"]["bytes_in_use"] == 100


def test_memory_postmortem_provider_carries_watermarks(tmp_path):
    mon = memory.MemoryMonitor(soft_limit=0.9)
    mon.sample(devices=[_FakeDev("tpu0", 640, 1000)])
    memory.install_postmortem_provider(mon)
    try:
        out = postmortem.capture("test",
                                 path=str(tmp_path / "pm.json"))
        doc = json.loads((tmp_path / "pm.json").read_text())
        state = doc["postmortem_state"][memory.STATE_PROVIDER_NAME]
        assert state["watermarks"] == {"tpu0": 640}
        assert state["soft_limit"] == 0.9
        assert out == str(tmp_path / "pm.json")
    finally:
        postmortem.unregister_state_provider(
            memory.STATE_PROVIDER_NAME)


def test_device_memory_stats_on_cpu_backend_degrades():
    """The real CPU backend reports no allocator stats — the
    documented degraded answer is an empty dict, not a raise."""
    import jax

    assert memory.device_memory_stats(jax.local_devices()) == {}


# -- profiler capture -------------------------------------------------

def test_profiler_capture_produces_artifact(tmp_path, monkeypatch):
    monkeypatch.setenv(profiler.OUT_DIR_ENV, str(tmp_path))
    cap = profiler.ProfileCapture()
    result = cap.capture(seconds=0.05)
    assert result["artifact"].startswith(str(tmp_path))
    assert os.path.isdir(result["artifact"])
    # jax.profiler wrote something into the artifact directory.
    assert any(os.scandir(result["artifact"]))
    events = [e for e in obs.TRACER.snapshot()["events"]
              if e["name"] == profiler.CAPTURE_EVENT]
    assert events and events[0]["fields"]["artifact"] \
        == result["artifact"]
    assert cap.last() == result


def test_profiler_serialized_second_caller_busy():
    cap = profiler.ProfileCapture()
    entered = threading.Event()
    release = threading.Event()

    def hold():
        with cap._lock:
            entered.set()
            release.wait(timeout=10)

    t = threading.Thread(target=hold)
    t.start()
    entered.wait(timeout=10)
    try:
        with pytest.raises(profiler.ProfilerBusy):
            cap.capture(seconds=0.01)
    finally:
        release.set()
        t.join(timeout=10)


def test_profile_response_status_codes(monkeypatch, tmp_path):
    assert profiler.profile_response("/debug/varz") is None
    status, ctype, body = profiler.profile_response(
        "/debug/profile", "seconds=abc")
    assert status == 400
    # Busy surface -> 409 with a machine-readable body.
    monkeypatch.setattr(profiler, "CAPTURE",
                        profiler.ProfileCapture())
    assert profiler.CAPTURE._lock.acquire(blocking=False)
    try:
        status, _, body = profiler.profile_response(
            "/debug/profile", "seconds=0.01")
        assert status == 409
        assert json.loads(body)["busy"] is True
    finally:
        profiler.CAPTURE._lock.release()
    # Unavailable backend -> documented 501 error JSON.
    import jax

    def boom(*a, **kw):
        raise RuntimeError("no profiler here")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    status, _, body = profiler.profile_response(
        "/debug/profile", "seconds=0.01")
    assert status == 501
    doc = json.loads(body)
    assert doc["available"] is False and "error" in doc
    # Available backend -> 200 + artifact.
    monkeypatch.undo()
    monkeypatch.setenv(profiler.OUT_DIR_ENV, str(tmp_path))
    monkeypatch.setattr(profiler, "CAPTURE",
                        profiler.ProfileCapture())
    status, _, body = profiler.profile_response(
        "/debug/profile", "seconds=0.02")
    assert status == 200
    doc = json.loads(body)
    assert doc["ok"] is True and os.path.isdir(doc["artifact"])


# -- serving SLO metrics on a real engine-mode server -----------------

def _get(port, path):
    with urllib.request.urlopen(
            f"http://localhost:{port}{path}", timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_engine_request_populates_slo_metrics(monkeypatch, tmp_path):
    """The tier-1 acceptance path: one real greedy engine-mode
    request (CPU fake backend) must populate the TTFT/TPOT
    histograms (in /stats percentiles AND Prometheus text), burn the
    SLO counter against an absurdly tight threshold, report the hbm_*
    stats keys, stay token-identical to per-request decode(), and
    serve a serialized /debug/profile capture."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from container_engine_accelerators_tpu.models import (
        TransformerLM,
    )
    from container_engine_accelerators_tpu.models.decode import (
        decode,
    )
    from container_engine_accelerators_tpu.serving import (
        GenerationServer,
    )
    from container_engine_accelerators_tpu.serving.server import (
        SLO_COUNTER,
        TPOT_HISTOGRAM,
        TTFT_HISTOGRAM,
    )

    # Impossible-to-meet SLOs: every observation is a violation, so
    # the burn counter provably wires through. Read at engine
    # construction, hence set before the server exists.
    monkeypatch.setenv("CEA_TPU_SLO_TTFT_MS", "0.0001")
    monkeypatch.setenv("CEA_TPU_SLO_TPOT_MS", "0.0001")
    # Rate the CPU rig so the decode-MFU gauge publishes too.
    monkeypatch.setenv("CEA_TPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv(profiler.OUT_DIR_ENV, str(tmp_path))
    monkeypatch.setattr(profiler, "CAPTURE",
                        profiler.ProfileCapture())

    model = TransformerLM(vocab_size=48, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer("lm", model, params, port=0,
                           max_new_tokens=8, max_batch=2,
                           buckets=[8])
    assert srv._engine_service is not None
    srv.start()
    try:
        prompts = [[1, 2, 3, 4], [5, 6, 7, 8]]
        new = 6
        req = urllib.request.Request(
            f"http://localhost:{srv.port}/v1/models/lm:generate",
            data=json.dumps({"prompts": prompts,
                             "max_new_tokens": new}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())

        # Greedy engine output stays token-identical to per-request
        # decode — the instrumentation perturbed nothing.
        padded = np.zeros((2, 8), np.int32)
        padded[:, :4] = np.asarray(prompts, np.int32)
        ref = np.asarray(decode(
            model, params, jnp.asarray(padded), new,
            prompt_len=np.array([4, 4]), fast_prefill=False))
        for i, seq in enumerate(out["sequences"]):
            assert seq == ref[i][:4 + new].tolist()

        _, stats = _get(srv.port, "/stats")
        assert stats["ttft_p50_ms"] is not None
        assert stats["ttft_p99_ms"] is not None
        assert stats["tpot_p50_ms"] is not None
        assert stats["tpot_p99_ms"] is not None
        # 2 TTFT observations; (new-1) TPOT observations per row.
        assert stats["slo"]["ttft_ms"] == pytest.approx(0.0001)
        assert stats["slo"]["violations"]["ttft"] == 2
        assert stats["slo"]["violations"]["tpot"] == 2 * (new - 1)
        assert "hbm_in_use_bytes" in stats
        assert "hbm_peak_bytes" in stats
        assert stats["decode_mfu"] is not None \
            and stats["decode_mfu"] > 0

        # Histograms populated with non-zero counts, scrapeable.
        hists = {h.name: h for h in obs.TRACER.histograms()}
        assert hists[TTFT_HISTOGRAM].count == 2
        assert hists[TPOT_HISTOGRAM].count == 2 * (new - 1)
        text = obs.prometheus_text(obs.TRACER)
        assert f"{TTFT_HISTOGRAM}_bucket" in text
        assert f"{TPOT_HISTOGRAM}_bucket" in text
        assert f'{SLO_COUNTER}{{slo="ttft"}} 2' in text

        # /debug/profile: 200 + artifact when free, 409 while held.
        status, doc = _get(srv.port, "/debug/profile?seconds=0.02")
        assert status == 200 and os.path.isdir(doc["artifact"])
        assert profiler.CAPTURE._lock.acquire(blocking=False)
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(srv.port, "/debug/profile?seconds=0.02")
            assert err.value.code == 409
        finally:
            profiler.CAPTURE._lock.release()
    finally:
        srv.stop()
