# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Explicit-collective tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.parallel import build_mesh
from container_engine_accelerators_tpu.parallel.collectives import (
    all_gather,
    all_reduce_mean,
    reduce_scatter,
    ring_all_reduce,
)
from container_engine_accelerators_tpu.parallel.distributed import (
    initialize_from_plugin_env,
)
from container_engine_accelerators_tpu.parallel.mesh import MeshSpec


@pytest.fixture(scope="module")
def mesh():
    return build_mesh()  # 8-way data axis


def test_all_reduce_mean(mesh):
    x = jnp.arange(16.0).reshape(16, 1)
    out = all_reduce_mean(mesh, x)
    # Each device holds 2 rows; pmean averages over devices per
    # position within the shard.
    expect = np.mean(np.arange(16.0).reshape(8, 2), axis=0)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 2)[0], expect)


def test_all_gather(mesh):
    x = jnp.arange(8.0)
    out = all_gather(mesh, x)
    np.testing.assert_allclose(out, np.arange(8.0))


def test_reduce_scatter(mesh):
    x = jnp.ones((8, 4))
    out = reduce_scatter(mesh, x)
    # Global view: each device's (1,4) chunk holds the 8-way sum;
    # reassembled along the data axis that is (8,4) of 8.0.
    assert out.shape == (8, 4)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))


def test_ring_all_reduce_matches_psum(mesh):
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 16))
    ring = ring_all_reduce(mesh, x)
    # psum equivalent via pmean * n on same sharding
    want = all_reduce_mean(mesh, x) * 8.0
    np.testing.assert_allclose(np.asarray(ring), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_all_reduce_single_device():
    mesh = build_mesh(MeshSpec(data=1), devices=jax.devices()[:1])
    x = jnp.ones((4, 4))
    np.testing.assert_allclose(ring_all_reduce(mesh, x), x)


def test_initialize_single_host_is_noop(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    assert initialize_from_plugin_env() is False
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    assert initialize_from_plugin_env() is False


def test_ring_all_reduce_non_divisible_shard(mesh):
    # Per-device shard of 3 elements doesn't divide into 8 blocks;
    # the padded schedule must still match psum semantics.
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 3))
    ring = ring_all_reduce(mesh, x)
    want = all_reduce_mean(mesh, x) * 8.0
    np.testing.assert_allclose(np.asarray(ring), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
