# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Speculative decoding: speculation may only change wall-clock.

Greedy (temperature 0): exact token equality is the contract — every
greedy test pins speculative_decode against plain greedy decode().
Sampling (temperature > 0, rejection-sampling speculation): the
contract is DISTRIBUTIONAL — committed tokens must follow the
target's softmax(logits/T) exactly, which the sampling tests check
against enumerated exact marginals (plus structural invariants:
reproducibility under a fixed rng, self-draft full acceptance, the
T->0 greedy limit, EOS/ragged semantics). The verify path
(multi-token chunks attending a non-empty cache via
chunk_attends_cache) is exercised by construction in every case.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import TransformerLM
from container_engine_accelerators_tpu.models.decode import decode
from container_engine_accelerators_tpu.models.speculative import (
    speculative_decode,
)

# Tier-1 budget: this module compiles many distinct XLA programs and
# runs minutes on the CI CPU mesh. It only became collectable when the
# shard_map compat shim fixed the jax-version import error, and
# including it would blow the 870s tier-1 cap — so it runs in the full
# lane (`make test` / pytest without `-m "not slow"`) instead.
pytestmark = pytest.mark.slow



def _make(vocab=64, embed=32, layers=2, heads=4, seq=96, seed=0,
          **kwargs):
    model = TransformerLM(vocab_size=vocab, embed_dim=embed,
                          num_layers=layers, num_heads=heads,
                          max_seq_len=seq, dtype=jnp.float32, **kwargs)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompt(b, p, vocab=64, seed=7):
    return jax.random.randint(jax.random.PRNGKey(seed), (b, p), 0,
                              vocab)


@pytest.mark.parametrize("k", [1, 2, 4, 7])
def test_spec_equals_greedy_disagreeing_draft(k):
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 8)
    want = decode(target, tp, prompt, 16)
    got = speculative_decode(target, tp, draft, dp, prompt, 16, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_equals_greedy_self_draft_full_acceptance():
    """Draft == target: every proposal matches, each round commits k
    tokens, and the output is still exactly greedy."""
    target, tp = _make(seed=0)
    prompt = _prompt(1, 8)
    want = decode(target, tp, prompt, 20)
    got, stats = speculative_decode(target, tp, target, tp, prompt,
                                    20, k=4, return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert int(stats["accepted_drafts"]) > 0
    # Full acceptance commits k tokens per round (k-1 drafts + the
    # target's own token, which equals the k-th draft).
    assert int(stats["rounds"]) <= -(-20 // 4)  # ceil


@pytest.mark.parametrize("kwargs", [
    {"pos_embedding": "rope"},
    {"num_kv_heads": 2},
    {"kv_cache_dtype": "int8"},
    {"pos_embedding": "rope", "num_kv_heads": 2,
     "kv_cache_dtype": "int8"},
])
def test_spec_equals_greedy_model_variants(kwargs):
    target, tp = _make(seed=3, **kwargs)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=4, **{
        key: val for key, val in kwargs.items()
        if key != "num_kv_heads"})
    prompt = _prompt(2, 8)
    want = decode(target, tp, prompt, 12)
    got = speculative_decode(target, tp, draft, dp, prompt, 12, k=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_batch_uniform_progress():
    """Batched rows advance by the minimum acceptance; output still
    matches row-for-row."""
    target, tp = _make(seed=0)
    prompt = _prompt(4, 8, seed=11)
    want = decode(target, tp, prompt, 16)
    got = speculative_decode(target, tp, target, tp, prompt, 16, k=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_validation():
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=1)
    prompt = _prompt(1, 8)
    with pytest.raises(ValueError, match="max_new_tokens >= 1"):
        speculative_decode(target, tp, draft, dp, prompt, 0)
    with pytest.raises(ValueError, match="k must be"):
        speculative_decode(target, tp, draft, dp, prompt, 4, k=0)
    vdraft, vdp = _make(vocab=32, embed=16, layers=1, heads=2, seed=1)
    with pytest.raises(ValueError, match="vocab"):
        speculative_decode(target, tp, vdraft, vdp, prompt, 4)
    with pytest.raises(ValueError, match="max_seq_len"):
        speculative_decode(target, tp, draft, dp, prompt, 96, k=4)
    from container_engine_accelerators_tpu.models import (
        MoETransformerLM,
    )
    # MoE with DROPPY routing (capacity_factor * top_k < num_experts)
    # must raise — drop patterns are token-group-shaped, so verify
    # chunks would score tokens differently than decode steps.
    moe = MoETransformerLM(vocab_size=64, embed_dim=32, num_layers=1,
                           num_heads=2, num_experts=8, top_k=2,
                           capacity_factor=1.25, max_seq_len=96,
                           dtype=jnp.float32)
    with pytest.raises(ValueError, match="drop-free"):
        speculative_decode(moe, {}, draft, dp, prompt, 4)
    with pytest.raises(ValueError, match="drop-free"):
        speculative_decode(target, tp, moe, {}, prompt, 4)


def test_spec_equals_greedy_ragged_prompts():
    """prompt_len support: rows with different true lengths match
    decode(prompt_len=...) token-for-token (the serving layer's
    padded-bucket shape)."""
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=9)
    prompt = _prompt(3, 8, seed=13)
    plen = jnp.array([3, 8, 5], jnp.int32)
    want = decode(target, tp, prompt, 12, prompt_len=plen)
    for dm, dpar in ((draft, dp), (target, tp)):
        got = speculative_decode(target, tp, dm, dpar, prompt, 12,
                                 k=4, prompt_len=plen)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))


def test_spec_ragged_validation():
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=9)
    prompt = _prompt(2, 8)
    with pytest.raises(ValueError, match="prompt_len"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           prompt_len=jnp.array([0, 8]))
    with pytest.raises(ValueError, match="prompt_len"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           prompt_len=9)


def test_spec_ragged_wrong_length_vector():
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=9)
    prompt = _prompt(3, 8)
    with pytest.raises(ValueError, match="one entry per row"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           prompt_len=jnp.array([3, 5]))


def _eos_token(model, params, prompt, n=20):
    """A token id that actually appears in the greedy generation, so
    EOS tests exercise real terminations."""
    import collections
    gen = np.asarray(decode(model, params, prompt, n))[:,
                                                       prompt.shape[1]:]
    return collections.Counter(gen.flatten().tolist()).most_common(
        1)[0][0]


def test_spec_equals_greedy_with_eos():
    """EOS semantics match decode (finished rows keep emitting EOS),
    for scalar, per-row mixed (-1 = off), and ragged+eos together."""
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=9)
    prompt = _prompt(3, 8, seed=13)
    eos = _eos_token(target, tp, prompt)
    for eos_arg in (eos, jnp.array([eos, -1, eos], jnp.int32)):
        want = decode(target, tp, prompt, 20, eos_id=eos_arg)
        for dm, dpar in ((draft, dp), (target, tp)):
            got = speculative_decode(target, tp, dm, dpar, prompt,
                                     20, k=4, eos_id=eos_arg)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
    plen = jnp.array([3, 8, 5], jnp.int32)
    want = decode(target, tp, prompt, 20, eos_id=eos,
                  prompt_len=plen)
    got = speculative_decode(target, tp, draft, dp, prompt, 20, k=4,
                             eos_id=eos, prompt_len=plen)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_eos_early_exit():
    """Once every row finished, the loop exits and fills EOS without
    further model evaluations — decode cannot do that. generated <
    max_new_tokens proves the early exit fired."""
    target, tp = _make(seed=0)
    prompt = _prompt(2, 8, seed=13)
    eos = _eos_token(target, tp, prompt)
    want = decode(target, tp, prompt, 40, eos_id=eos)
    got, stats = speculative_decode(target, tp, target, tp, prompt,
                                    40, k=4, eos_id=eos,
                                    return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # Both rows terminate well before 40 tokens in this fixture; the
    # early exit must have stopped the loop short.
    assert int(stats["generated"]) < 40, stats


def test_spec_eos_validation():
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=9)
    prompt = _prompt(2, 8)
    with pytest.raises(ValueError, match="eos_id"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           eos_id=jnp.array([1, 2, 3]))
    with pytest.raises(ValueError, match="eos_id"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           eos_id=64)


# ---------------------------------------------------------------------
# Rejection-sampling speculation (temperature > 0)
# ---------------------------------------------------------------------


def _small(vocab=16, seed=0, **kw):
    return _make(vocab=vocab, embed=kw.pop("embed", 32),
                 layers=kw.pop("layers", 2), heads=kw.pop("heads", 4),
                 seq=32, seed=seed, **kw)


def _marginals(model, params, prompt, temperature):
    """Exact per-position marginals P(x_{p}), P(x_{p+1}), P(x_{p+2})
    of ancestral sampling from softmax(logits/T), by enumerating all
    vocab^j prefixes (teacher-forced full forwards, no cache)."""
    V = model.vocab_size

    def last_probs(seqs):
        logits = model.apply({"params": params}, jnp.asarray(seqs),
                             train=False)
        if isinstance(logits, tuple):
            logits = logits[0]
        return np.asarray(jax.nn.softmax(
            logits[:, -1].astype(jnp.float32) / temperature, -1))

    p1 = last_probs(prompt)[0]                              # [V]
    toks = np.arange(V, dtype=np.int32)
    pre2 = np.concatenate(
        [np.repeat(prompt, V, 0), toks[:, None]], 1)
    cond2 = last_probs(pre2)                                # [V, V]
    p2 = p1 @ cond2
    pre3 = np.concatenate(
        [np.repeat(prompt, V * V, 0),
         np.repeat(toks, V)[:, None],
         np.tile(toks, V)[:, None]], 1)
    cond3 = last_probs(pre3).reshape(V, V, V)               # [t1,t2,V]
    p3 = np.einsum("a,ab,abv->v", p1, cond2, cond3)
    return p1, p2, p3


def _tv(a, b):
    return 0.5 * float(np.abs(np.asarray(a) - np.asarray(b)).sum())


def test_spec_sampling_matches_target_distribution():
    """THE correctness property of rejection-sampling speculation:
    committed tokens are distributed exactly per the TARGET's
    softmax(logits/T), not the draft's, even though most tokens are
    physically produced by the draft. Checked against exact
    enumerated marginals at the first three generated positions
    (positions 2-3 ride the accept/residual machinery); the draft is
    far from the target (TV ~ 0.4) so committing draft proposals
    unconditionally would fail these bounds by an order of
    magnitude."""
    V = 16
    target, tp = _small(vocab=V, seed=0)
    draft, dp = _small(vocab=V, embed=16, layers=1, heads=2, seed=99)
    prompt = np.array([[1, 2, 3, 4]], np.int32)
    T = 1.0
    p1, p2, p3 = _marginals(target, tp, prompt, T)
    d1, d2, d3 = _marginals(draft, dp, prompt, T)
    # Guard: the fixture must keep the two models distinguishable,
    # or this test can't tell "target-distributed" from "draft-
    # distributed".
    assert _tv(p2, d2) > 0.25 and _tv(p3, d3) > 0.25

    B, seeds, new = 128, 32, 3
    batch = np.repeat(prompt, B, 0)
    counts = np.zeros((3, V))
    for s in range(seeds):
        out = np.asarray(speculative_decode(
            target, tp, draft, dp, batch, new, k=4, temperature=T,
            rng=jax.random.PRNGKey(1000 + s)))
        gen = out[:, prompt.shape[1]:]
        for j in range(3):
            counts[j] += np.bincount(gen[:, j], minlength=V)
    emp = counts / counts.sum(axis=1, keepdims=True)
    # ~4k samples over 16 bins: TV noise floor ~0.02-0.03.
    for j, exact in enumerate((p1, p2, p3)):
        assert _tv(emp[j], exact) < 0.08, (j, _tv(emp[j], exact))
    # ...and provably NOT the draft's distribution.
    assert _tv(emp[1], d2) > 0.25
    assert _tv(emp[2], d3) > 0.25


def test_spec_sampling_self_draft_accepts_everything():
    """p == q makes the accept ratio exactly 1: every proposal
    accepted, every round commits k tokens."""
    target, tp = _small(seed=0)
    prompt = _prompt(2, 6, vocab=16)
    out, st = speculative_decode(
        target, tp, target, tp, prompt, 12, k=4, temperature=0.7,
        rng=jax.random.PRNGKey(3), return_stats=True)
    assert int(st["accepted_drafts"]) == 3 * int(st["rounds"]), st
    assert out.shape == (2, 6 + 12)


def test_spec_sampling_reproducible_and_seed_sensitive():
    target, tp = _small(seed=0)
    draft, dp = _small(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 6, vocab=16)
    r = jax.random.PRNGKey(5)
    a = speculative_decode(target, tp, draft, dp, prompt, 10, k=4,
                           temperature=1.0, rng=r)
    b = speculative_decode(target, tp, draft, dp, prompt, 10, k=4,
                           temperature=1.0, rng=r)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = speculative_decode(target, tp, draft, dp, prompt, 10, k=4,
                           temperature=1.0,
                           rng=jax.random.PRNGKey(6))
    assert (np.asarray(a) != np.asarray(c)).any()


def test_spec_sampling_tiny_temperature_is_greedy():
    """T -> 0 collapses both p and q to argmax one-hots, so the
    sampling program must reproduce the greedy token path."""
    target, tp = _small(seed=0)
    draft, dp = _small(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 6, vocab=16)
    want = decode(target, tp, prompt, 10)
    got = speculative_decode(target, tp, draft, dp, prompt, 10, k=4,
                             temperature=1e-5,
                             rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_sampling_eos_semantics():
    """Sampling + EOS: decode's keep-emitting contract holds — after
    the first generated EOS every later position is EOS."""
    target, tp = _small(seed=0)
    draft, dp = _small(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 6, vocab=16)
    p = prompt.shape[1]
    eos = 3
    out = np.asarray(speculative_decode(
        target, tp, draft, dp, prompt, 20, k=4, temperature=1.0,
        rng=jax.random.PRNGKey(11), eos_id=eos))
    gen = out[:, p:]
    for row in gen:
        hits = np.nonzero(row == eos)[0]
        if hits.size:
            assert (row[hits[0]:] == eos).all(), row


def test_spec_sampling_ragged_prompts_keep_prompt_region():
    """Sampling + ragged: forced prompt tokens survive verbatim; the
    padding region is generated (whatever it is, the row's true
    prompt must not be disturbed)."""
    target, tp = _small(seed=0)
    draft, dp = _small(embed=16, layers=1, heads=2, seed=99)
    prompt = np.asarray(_prompt(2, 8, vocab=16))
    plen = np.array([5, 8], np.int32)
    out = np.asarray(speculative_decode(
        target, tp, draft, dp, prompt, 8, k=4, temperature=1.0,
        rng=jax.random.PRNGKey(12), prompt_len=plen))
    for r, pl in enumerate(plen):
        np.testing.assert_array_equal(out[r, :pl], prompt[r, :pl])
    assert out.shape == (2, 16)


def test_spec_sampling_validation():
    target, tp = _small(seed=0)
    draft, dp = _small(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 6, vocab=16)
    with pytest.raises(ValueError, match="all zero .* or all"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           temperature=jnp.array([0.0, 1.0]))
    with pytest.raises(ValueError, match=">= 0"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           temperature=-1.0)
    with pytest.raises(ValueError, match="temperature must be"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           temperature=jnp.ones((3,)))


# ---------------------------------------------------------------------
# MoE targets/drafts (drop-free routing)
# ---------------------------------------------------------------------


def _moe(vocab=64, experts=4, seed=0, **kw):
    from container_engine_accelerators_tpu.models import (
        MoETransformerLM,
    )

    # capacity_factor * top_k >= num_experts => drop-free: routing is
    # per-token, so chunked verify == stepwise decode exactly.
    model = MoETransformerLM(
        vocab_size=vocab, embed_dim=kw.pop("embed", 32),
        num_layers=kw.pop("layers", 2), num_heads=kw.pop("heads", 2),
        num_experts=experts, top_k=2, capacity_factor=experts / 2,
        max_seq_len=kw.pop("seq", 96), dtype=jnp.float32, **kw)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def test_spec_equals_greedy_moe_target():
    """Drop-free MoE target + dense draft: exact greedy identity."""
    target, tp = _moe(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 8)
    want = decode(target, tp, prompt, 12)
    got = speculative_decode(target, tp, draft, dp, prompt, 12, k=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_equals_greedy_moe_draft():
    """Dense target + drop-free MoE draft: exact greedy identity."""
    target, tp = _make(seed=0)
    draft, dp = _moe(embed=16, layers=1, experts=2, seed=99)
    prompt = _prompt(2, 8)
    want = decode(target, tp, prompt, 12)
    got = speculative_decode(target, tp, draft, dp, prompt, 12, k=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_moe_self_draft_full_acceptance():
    """MoE self-draft: every proposal must be accepted — the chunked
    verify scores EXACTLY like the draft's stepwise decode, which is
    precisely what drop-free routing guarantees (a droppy config
    would fail this test, not just the validation)."""
    target, tp = _moe(seed=0)
    prompt = _prompt(1, 8)
    out, st = speculative_decode(target, tp, target, tp, prompt, 12,
                                 k=4, return_stats=True)
    want = decode(target, tp, prompt, 12)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))
    assert int(st["accepted_drafts"]) == 3 * int(st["rounds"]), st


def test_spec_moe_sampling_reproducible_and_greedy_limit():
    target, tp = _moe(vocab=16, seed=0)
    draft, dp = _make(vocab=16, embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 6, vocab=16)
    r = jax.random.PRNGKey(5)
    a = speculative_decode(target, tp, draft, dp, prompt, 8, k=3,
                           temperature=1.0, rng=r)
    b = speculative_decode(target, tp, draft, dp, prompt, 8, k=3,
                           temperature=1.0, rng=r)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    want = decode(target, tp, prompt, 8)
    got = speculative_decode(target, tp, draft, dp, prompt, 8, k=3,
                             temperature=1e-5,
                             rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------
# active_rows (serving pad-row masking)
# ---------------------------------------------------------------------


def test_spec_active_rows_pad_cannot_gate_real_rows():
    """A masked run must behave EXACTLY like a run over the active
    rows alone: same committed tokens for the real row AND the same
    rounds/acceptance stats — the pad rows' draft/target
    disagreement must not cap the batch's uniform acceptance
    (without masking, zero-prompt pad rows reject nearly every round
    and degrade serving speculation toward plain decode)."""
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    real = np.asarray(_prompt(1, 8, seed=21))
    padded = np.concatenate(
        [real, np.zeros((3, 8), np.int32)], axis=0)

    alone, st_alone = speculative_decode(
        target, tp, draft, dp, real, 16, k=4, return_stats=True)
    masked, st_masked = speculative_decode(
        target, tp, draft, dp, padded, 16, k=4,
        active_rows=[True, False, False, False], return_stats=True)
    np.testing.assert_array_equal(np.asarray(masked)[0],
                                  np.asarray(alone)[0])
    assert int(st_masked["rounds"]) == int(st_alone["rounds"]), (
        st_masked, st_alone)
    assert int(st_masked["accepted_drafts"]) == int(
        st_alone["accepted_drafts"])
    # Unmasked, the garbage pad rows DO gate acceptance — shown
    # under sampling, where acceptance is the p/q overlap and hence
    # nonzero for the real row (greedy acceptance between two random
    # models is ~0 for every row, so it can't demonstrate the gap).
    # Deterministic given the fixed rng.
    r = jax.random.PRNGKey(77)
    _, st_m = speculative_decode(
        target, tp, draft, dp, padded, 16, k=4, temperature=1.0,
        rng=r, active_rows=[True, False, False, False],
        return_stats=True)
    _, st_u = speculative_decode(
        target, tp, draft, dp, padded, 16, k=4, temperature=1.0,
        rng=r, return_stats=True)
    assert int(st_u["accepted_drafts"]) < int(
        st_m["accepted_drafts"]), (st_u, st_m)
    assert int(st_m["rounds"]) < int(st_u["rounds"]), (st_m, st_u)


def test_spec_active_rows_output_identity_all_modes():
    """Masked runs stay output-correct for real rows in every mode:
    greedy equals decode; sampling is reproducible; EOS semantics
    hold."""
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = np.asarray(_prompt(2, 8, seed=22))
    padded = np.concatenate(
        [prompt, np.zeros((2, 8), np.int32)], axis=0)
    active = [True, True, False, False]

    want = decode(target, tp, prompt, 12)
    got = speculative_decode(target, tp, draft, dp, padded, 12, k=4,
                             active_rows=active)
    np.testing.assert_array_equal(np.asarray(got)[:2],
                                  np.asarray(want))

    r = jax.random.PRNGKey(6)
    s1 = speculative_decode(target, tp, draft, dp, padded, 8, k=3,
                            temperature=1.0, rng=r,
                            active_rows=active)
    s2 = speculative_decode(target, tp, draft, dp, padded, 8, k=3,
                            temperature=1.0, rng=r,
                            active_rows=active)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))

    eos = int(np.asarray(decode(target, tp, prompt, 1))[0, -1])
    out = np.asarray(speculative_decode(
        target, tp, draft, dp, padded, 16, k=4, eos_id=eos,
        active_rows=active))
    for row in out[:2, 8:]:
        hits = np.nonzero(row == eos)[0]
        if hits.size:
            assert (row[hits[0]:] == eos).all(), row


def test_spec_active_rows_validation():
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 8)
    with pytest.raises(ValueError, match="one entry per row"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           active_rows=[True])
    with pytest.raises(ValueError, match="at least one row"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           active_rows=[False, False])


# ---------------------------------------------------------------------
# Logprobs under speculation
# ---------------------------------------------------------------------


def test_spec_logprobs_match_decode_greedy():
    """Greedy + return_logprobs: tokens exactly equal decode's and
    scores match decode's raw-logit log-softmax (the verify chunk
    re-derives what decode computes stepwise), full-width and
    ragged."""
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 8)
    ws, wl = decode(target, tp, prompt, 12, return_logprobs=True)
    gs, gl = speculative_decode(target, tp, draft, dp, prompt, 12,
                                k=4, return_logprobs=True)
    np.testing.assert_array_equal(np.asarray(gs), np.asarray(ws))
    np.testing.assert_allclose(np.asarray(gl), np.asarray(wl),
                               atol=1e-5)
    plen = jnp.array([3, 8], jnp.int32)
    ws2, wl2 = decode(target, tp, prompt, 12, prompt_len=plen,
                      return_logprobs=True)
    gs2, gl2 = speculative_decode(target, tp, draft, dp, prompt, 12,
                                  k=3, prompt_len=plen,
                                  return_logprobs=True)
    np.testing.assert_array_equal(np.asarray(gs2), np.asarray(ws2))
    np.testing.assert_allclose(np.asarray(gl2), np.asarray(wl2),
                               atol=1e-5)


def test_spec_logprobs_sampling_self_consistent():
    """Sampling + return_logprobs: reported scores must equal the
    target's own teacher-forced log-softmax of the emitted sequence
    (raw logits, pre-temperature) — checkable exactly without any
    distributional argument."""
    target, tp = _make(vocab=16, seed=0)
    draft, dp = _make(vocab=16, embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 6, vocab=16)
    (seq, lps), st = speculative_decode(
        target, tp, draft, dp, prompt, 10, k=3, temperature=0.9,
        rng=jax.random.PRNGKey(5), return_logprobs=True,
        return_stats=True)
    logits = target.apply({"params": tp}, seq, train=False)
    if isinstance(logits, tuple):
        logits = logits[0]
    lsm = np.asarray(jax.nn.log_softmax(
        np.asarray(logits, np.float32), -1))
    want = np.take_along_axis(
        lsm[:, :-1], np.asarray(seq)[:, 1:, None], 2)[..., 0]
    np.testing.assert_allclose(np.asarray(lps)[:, 1:], want,
                               atol=1e-4)
    assert float(np.asarray(lps)[0, 0]) == 0.0


def test_spec_logprobs_with_eos_runs_to_max_new():
    """EOS + logprobs: the early exit is disabled (every position
    needs a real score); forced-EOS emissions score like decode's."""
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 8)
    eos = int(np.asarray(decode(target, tp, prompt, 1))[0, -1])
    ws, wl = decode(target, tp, prompt, 16, eos_id=eos,
                    return_logprobs=True)
    gs, gl = speculative_decode(target, tp, draft, dp, prompt, 16,
                                k=4, eos_id=eos,
                                return_logprobs=True)
    np.testing.assert_array_equal(np.asarray(gs), np.asarray(ws))
    np.testing.assert_allclose(np.asarray(gl), np.asarray(wl),
                               atol=1e-5)


# ---------------------------------------------------------------------
# Filtered sampling under speculation (top-k / top-p / min-p)
# ---------------------------------------------------------------------


def _filtered_marginals(model, params, prompt, temperature, top_p):
    """Exact marginals of ancestral sampling from the FILTERED
    distribution softmax(mask_top_p(logits/T)) — decode's own mask
    helper is the authority, applied exactly as decode.pick does."""
    from container_engine_accelerators_tpu.models.decode import (
        _mask_top_p,
    )

    V = model.vocab_size

    def probs(seqs):
        logits = model.apply({"params": params}, jnp.asarray(seqs),
                             train=False)
        if isinstance(logits, tuple):
            logits = logits[0]
        scaled = logits[:, -1].astype(jnp.float32) / temperature
        masked = _mask_top_p(scaled, jnp.full((scaled.shape[0],),
                                              top_p, jnp.float32))
        return np.asarray(jax.nn.softmax(masked, -1))

    p1 = probs(prompt)[0]
    toks = np.arange(V, dtype=np.int32)
    pre2 = np.concatenate([np.repeat(prompt, V, 0), toks[:, None]], 1)
    cond2 = probs(pre2)
    p2 = p1 @ cond2
    pre3 = np.concatenate(
        [np.repeat(prompt, V * V, 0),
         np.repeat(toks, V)[:, None], np.tile(toks, V)[:, None]], 1)
    cond3 = probs(pre3).reshape(V, V, V)
    p3 = np.einsum("a,ab,abv->v", p1, cond2, cond3)
    return p1, p2, p3


def test_spec_filtered_sampling_matches_filtered_target():
    """top-p speculation must produce tokens distributed exactly per
    the target's NUCLEUS-FILTERED softmax — checked against exact
    enumerated filtered marginals at the first three generated
    positions. The filter bites hard (TV vs the unfiltered target
    > 0.2) and the result is far from the draft's filtered
    distribution, so neither 'filters ignored' nor 'draft leaked
    through' can pass."""
    V = 16
    target, tp = _small(vocab=V, seed=0)
    draft, dp = _small(vocab=V, embed=16, layers=1, heads=2, seed=99)
    prompt = np.array([[1, 2, 3, 4]], np.int32)
    T, TOP_P = 1.0, 0.7
    f1, f2, f3 = _filtered_marginals(target, tp, prompt, T, TOP_P)
    u1, u2, u3 = _marginals(target, tp, prompt, T)     # unfiltered
    d1, d2, d3 = _filtered_marginals(draft, dp, prompt, T, TOP_P)
    assert _tv(f2, u2) > 0.15 and _tv(f3, u3) > 0.1, (
        _tv(f2, u2), _tv(f3, u3))
    assert _tv(f2, d2) > 0.25 and _tv(f3, d3) > 0.25

    B, seeds = 128, 32
    batch = np.repeat(prompt, B, 0)
    counts = np.zeros((3, V))
    for s in range(seeds):
        out = np.asarray(speculative_decode(
            target, tp, draft, dp, batch, 3, k=4, temperature=T,
            top_p=TOP_P, rng=jax.random.PRNGKey(3000 + s)))
        gen = out[:, prompt.shape[1]:]
        for j in range(3):
            counts[j] += np.bincount(gen[:, j], minlength=V)
    emp = counts / counts.sum(axis=1, keepdims=True)
    for j, exact in enumerate((f1, f2, f3)):
        assert _tv(emp[j], exact) < 0.08, (j, _tv(emp[j], exact))
    assert _tv(emp[1], u2) > 0.1      # filters were NOT ignored
    assert _tv(emp[1], d2) > 0.2      # and it's not the draft


def test_spec_filtered_sampling_structure():
    """Structural invariants for every filter kind: reproducibility,
    filtered self-draft full acceptance (p' == q'), top_k=1 ==
    greedy, validation."""
    target, tp = _small(seed=0)
    draft, dp = _small(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 6, vocab=16)
    r = jax.random.PRNGKey(7)
    for kw in ({"top_k": 4}, {"top_p": 0.8}, {"min_p": 0.1},
               {"top_k": 8, "top_p": 0.9, "min_p": 0.05}):
        a = speculative_decode(target, tp, draft, dp, prompt, 6, k=3,
                               temperature=1.0, rng=r, **kw)
        bb = speculative_decode(target, tp, draft, dp, prompt, 6,
                                k=3, temperature=1.0, rng=r, **kw)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))
    want = decode(target, tp, prompt, 8)
    got = speculative_decode(target, tp, draft, dp, prompt, 8, k=3,
                             temperature=1.0, rng=r, top_k=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    out, st = speculative_decode(target, tp, target, tp, prompt, 9,
                                 k=4, temperature=0.8, rng=r,
                                 top_p=0.9, return_stats=True)
    assert int(st["accepted_drafts"]) == 3 * int(st["rounds"]), st
    # Greedy ignores filters, exactly like decode's argmax branch —
    # drop-in parity for callers that pass knobs unconditionally.
    got = speculative_decode(target, tp, draft, dp, prompt, 6, k=3,
                             top_k=3, top_p=0.9)
    np.testing.assert_array_equal(
        np.asarray(got),
        np.asarray(decode(target, tp, prompt, 6)))
    with pytest.raises(ValueError, match="top_p"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           temperature=1.0, top_p=0.0)
    with pytest.raises(ValueError, match="min_p"):
        speculative_decode(target, tp, draft, dp, prompt, 4,
                           temperature=1.0, min_p=1.0)


# ---------------------------------------------------------------------------
# Sliding-window (ring cache) speculation: output must equal plain
# WINDOWED decode exactly. Every config here wraps the ring
# (prompt + max_new well past the window), so the scatter chunk
# write, the ring_slack eviction margin, and the stale-slot masking
# are all load-bearing, not idle paths.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [1, 2, 4])
def test_spec_windowed_target_equals_windowed_greedy(k):
    target, tp = _make(seed=0, attention_window=8)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 8)
    want = decode(target, tp, prompt, 24)
    got = speculative_decode(target, tp, draft, dp, prompt, 24, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_windowed_draft_dense_target():
    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99,
                      attention_window=8)
    prompt = _prompt(2, 8)
    want = decode(target, tp, prompt, 24)
    got = speculative_decode(target, tp, draft, dp, prompt, 24, k=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_windowed_self_draft_full_acceptance():
    """Windowed target == windowed draft: proposals all match, so
    every round commits k tokens and the ring rewind machinery runs
    at maximum optimistic depth."""
    target, tp = _make(seed=0, attention_window=8)
    prompt = _prompt(1, 8)
    want = decode(target, tp, prompt, 24)
    got, stats = speculative_decode(target, tp, target, tp, prompt,
                                    24, k=4, return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert int(stats["accepted_drafts"]) > 0
    assert int(stats["rounds"]) <= -(-24 // 4)


def test_spec_windowed_ragged_and_eos():
    """Windowed speculation composes with ragged prompts and EOS,
    matching plain windowed decode's exact semantics."""
    target, tp = _make(seed=0, attention_window=8)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(3, 8, seed=5)
    plen = jnp.asarray([8, 3, 6], jnp.int32)
    want = decode(target, tp, prompt, 20, prompt_len=plen)
    got = speculative_decode(target, tp, draft, dp, prompt, 20, k=4,
                             prompt_len=plen)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # EOS: pick a token the greedy run actually emits so the done
    # machinery engages mid-sequence.
    eos = int(np.asarray(want)[0, 10])
    want_e = decode(target, tp, prompt, 20, eos_id=eos)
    got_e = speculative_decode(target, tp, draft, dp, prompt, 20,
                               k=4, eos_id=eos)
    np.testing.assert_array_equal(np.asarray(got_e),
                                  np.asarray(want_e))


def test_spec_windowed_composes_gqa_rope_int8():
    """Ring speculation on the serving stack's full composition:
    GQA + rope + int8 KV cache + sliding window."""
    kwargs = dict(num_kv_heads=2, pos_embedding="rope",
                  kv_cache_dtype="int8", attention_window=8)
    target, tp = _make(seed=0, **kwargs)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99, **kwargs)
    prompt = _prompt(2, 8)
    want = decode(target, tp, prompt, 24)
    got = speculative_decode(target, tp, draft, dp, prompt, 24, k=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_windowed_logprobs_match_decode():
    target, tp = _make(seed=0, attention_window=8)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 8)
    want, want_lp = decode(target, tp, prompt, 20,
                           return_logprobs=True)
    got, got_lp = speculative_decode(target, tp, draft, dp, prompt,
                                     20, k=4, return_logprobs=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_allclose(np.asarray(got_lp),
                               np.asarray(want_lp), atol=2e-4)


def test_spec_windowed_sampling_reproducible_and_greedy_limit():
    target, tp = _make(seed=0, attention_window=8)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(1, 8)
    rng = jax.random.PRNGKey(3)
    a = speculative_decode(target, tp, draft, dp, prompt, 16, k=4,
                           temperature=1.0, rng=rng)
    b = speculative_decode(target, tp, draft, dp, prompt, 16, k=4,
                           temperature=1.0, rng=rng)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(np.asarray(a).max()) < target.vocab_size
    # T -> 0 limit reproduces greedy windowed decode exactly.
    tiny = speculative_decode(target, tp, draft, dp, prompt, 16, k=4,
                              temperature=1e-6, rng=rng)
    want = decode(target, tp, prompt, 16)
    np.testing.assert_array_equal(np.asarray(tiny), np.asarray(want))


def test_spec_windowed_moe_target_equals_windowed_greedy():
    """Drop-free MoE target WITH a sliding window (ring_slack threads
    through the MoE block stack too): exact greedy identity against
    plain windowed MoE decode, ring wrapped several times."""
    target, tp = _moe(seed=0, attention_window=8)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prompt = _prompt(2, 8)
    want = decode(target, tp, prompt, 24)
    got = speculative_decode(target, tp, draft, dp, prompt, 24, k=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# Prefix-state speculation: a shared prefix prefilled ONCE per model
# (target + draft), requests pay suffix + drafted generation. Output
# must equal decode_with_prefix exactly (greedy) — the two serving
# levers (prefix caching, speculation) composed.
# ---------------------------------------------------------------------------


def _prefix_states(target, tp, draft, dp, prefix, max_total):
    from container_engine_accelerators_tpu.models.decode import (
        prefill_prefix,
    )

    return (prefill_prefix(target, tp, prefix, max_total_len=max_total),
            prefill_prefix(draft, dp, prefix, max_total_len=max_total))


@pytest.mark.parametrize("k", [1, 2, 4])
def test_spec_prefix_equals_decode_with_prefix(k):
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
    )
    from container_engine_accelerators_tpu.models.speculative import (
        speculative_decode_with_prefix,
    )

    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prefix = _prompt(1, 6, seed=21)
    suffixes = _prompt(2, 5, seed=22)
    t_state, d_state = _prefix_states(target, tp, draft, dp, prefix,
                                      6 + 5 + 16 + k)
    want = decode_with_prefix(target, tp, t_state, suffixes, 16)
    got = speculative_decode_with_prefix(
        target, tp, draft, dp, t_state, d_state, suffixes, 16, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_prefix_self_draft_full_acceptance_and_fan_out():
    """Self-draft over a fanned-out prefix (prefix batch 1 ->
    request batch 3): full acceptance, exact equality, and the
    round bound holds."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
    )
    from container_engine_accelerators_tpu.models.speculative import (
        speculative_decode_with_prefix,
    )

    target, tp = _make(seed=0)
    prefix = _prompt(1, 6, seed=23)
    suffixes = _prompt(3, 4, seed=24)
    t_state, d_state = _prefix_states(target, tp, target, tp, prefix,
                                      6 + 4 + 20 + 4)
    want = decode_with_prefix(target, tp, t_state, suffixes, 20)
    got, stats = speculative_decode_with_prefix(
        target, tp, target, tp, t_state, d_state, suffixes, 20, k=4,
        return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert int(stats["accepted_drafts"]) > 0
    assert int(stats["rounds"]) <= -(-20 // 4)


def test_spec_prefix_ragged_and_eos():
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
    )
    from container_engine_accelerators_tpu.models.speculative import (
        speculative_decode_with_prefix,
    )

    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prefix = _prompt(1, 6, seed=25)
    suffixes = _prompt(3, 5, seed=26)
    plen = jnp.asarray([5, 2, 4], jnp.int32)
    t_state, d_state = _prefix_states(target, tp, draft, dp, prefix,
                                      6 + 5 + 14 + 4)
    want = decode_with_prefix(target, tp, t_state, suffixes, 14,
                              prompt_len=plen)
    got = speculative_decode_with_prefix(
        target, tp, draft, dp, t_state, d_state, suffixes, 14, k=4,
        prompt_len=plen)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    eos = int(np.asarray(want)[0, 7])
    want_e = decode_with_prefix(target, tp, t_state, suffixes, 14,
                                eos_id=eos)
    got_e = speculative_decode_with_prefix(
        target, tp, draft, dp, t_state, d_state, suffixes, 14, k=4,
        eos_id=eos)
    np.testing.assert_array_equal(np.asarray(got_e),
                                  np.asarray(want_e))


def test_spec_prefix_composes_int8_gqa_rope():
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
    )
    from container_engine_accelerators_tpu.models.speculative import (
        speculative_decode_with_prefix,
    )

    kwargs = dict(num_kv_heads=2, pos_embedding="rope",
                  kv_cache_dtype="int8")
    target, tp = _make(seed=3, **kwargs)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=4, **kwargs)
    prefix = _prompt(1, 6, seed=27)
    suffixes = _prompt(2, 4, seed=28)
    t_state, d_state = _prefix_states(target, tp, draft, dp, prefix,
                                      6 + 4 + 12 + 3)
    want = decode_with_prefix(target, tp, t_state, suffixes, 12)
    got = speculative_decode_with_prefix(
        target, tp, draft, dp, t_state, d_state, suffixes, 12, k=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spec_prefix_sampling_reproducible_and_greedy_limit():
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
    )
    from container_engine_accelerators_tpu.models.speculative import (
        speculative_decode_with_prefix,
    )

    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prefix = _prompt(1, 6, seed=29)
    suffixes = _prompt(1, 4, seed=30)
    t_state, d_state = _prefix_states(target, tp, draft, dp, prefix,
                                      6 + 4 + 12 + 4)
    rng = jax.random.PRNGKey(5)
    a = speculative_decode_with_prefix(
        target, tp, draft, dp, t_state, d_state, suffixes, 12, k=4,
        temperature=1.0, rng=rng)
    b = speculative_decode_with_prefix(
        target, tp, draft, dp, t_state, d_state, suffixes, 12, k=4,
        temperature=1.0, rng=rng)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(np.asarray(a).max()) < target.vocab_size
    tiny = speculative_decode_with_prefix(
        target, tp, draft, dp, t_state, d_state, suffixes, 12, k=4,
        temperature=1e-6, rng=rng)
    want = decode_with_prefix(target, tp, t_state, suffixes, 12)
    np.testing.assert_array_equal(np.asarray(tiny), np.asarray(want))


def test_spec_prefix_validation():
    from container_engine_accelerators_tpu.models.decode import (
        prefill_prefix,
    )
    from container_engine_accelerators_tpu.models.speculative import (
        speculative_decode_with_prefix,
    )

    target, tp = _make(seed=0)
    draft, dp = _make(embed=16, layers=1, heads=2, seed=99)
    prefix = _prompt(1, 6, seed=31)
    suffixes = _prompt(2, 4, seed=32)
    t_state = prefill_prefix(target, tp, prefix, max_total_len=40)
    d_state = prefill_prefix(draft, dp, prefix, max_total_len=40)
    # Mismatched prefix lengths.
    d_short = prefill_prefix(draft, dp, prefix[:, :4],
                             max_total_len=40)
    with pytest.raises(ValueError, match="prefix length"):
        speculative_decode_with_prefix(
            target, tp, draft, dp, t_state, d_short, suffixes, 8)
    # Overflow of the state capacity.
    with pytest.raises(ValueError, match="overflows"):
        speculative_decode_with_prefix(
            target, tp, draft, dp, t_state, d_state, suffixes, 40)
    # Windowed models refuse.
    wtarget, wtp = _make(seed=0, attention_window=8)
    wt_state = prefill_prefix(wtarget, wtp, prefix, max_total_len=40)
    with pytest.raises(ValueError, match="sliding-window"):
        speculative_decode_with_prefix(
            wtarget, wtp, draft, dp, wt_state, d_state, suffixes, 8)
    # Request batch must be a multiple of the prefix batch.
    prefix2 = _prompt(2, 6, seed=34)
    t2 = prefill_prefix(target, tp, prefix2, max_total_len=40)
    d2 = prefill_prefix(draft, dp, prefix2, max_total_len=40)
    with pytest.raises(ValueError, match="multiple"):
        speculative_decode_with_prefix(
            target, tp, draft, dp, t2, d2, _prompt(3, 4, seed=33), 8)
