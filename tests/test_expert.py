# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Expert-parallel MoE tests on the 8-device CPU mesh.

In the no-drop regime the expert-parallel schedule is exact against
the single-device dense reference (slot positions differ across
routing groups, slot sums do not), so the core tests are equality
checks — the same strongest-property strategy test_context.py uses
for ring/Ulysses.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from container_engine_accelerators_tpu.models import MoETransformerLM
from container_engine_accelerators_tpu.models.moe import (
    MoEMlp,
    make_apply_fn,
    with_router_loss,
)
from container_engine_accelerators_tpu.models.transformer import (
    next_token_loss_fn,
)
from container_engine_accelerators_tpu.ops import mean_cross_entropy_loss
from container_engine_accelerators_tpu.parallel import (
    Trainer,
    batch_sharding,
    build_expert_mesh,
    dense_moe,
    expert_parallel_moe,
)
from container_engine_accelerators_tpu.parallel.expert import (
    EXPERT_AXIS,
    expert_capacity,
    top_k_routing,
)

T, D, F, E = 64, 16, 32, 4


@pytest.fixture(scope="module")
def weights():
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    tokens = jax.random.normal(ks[0], (T, D), jnp.float32)
    gate_w = jax.random.normal(ks[1], (D, E), jnp.float32)
    w_in = jax.random.normal(ks[2], (E, D, F), jnp.float32) * 0.1
    w_out = jax.random.normal(ks[3], (E, F, D), jnp.float32) * 0.1
    return tokens, gate_w, w_in, w_out


# -- routing ----------------------------------------------------------


def test_routing_respects_capacity():
    logits = jax.random.normal(jax.random.PRNGKey(0), (32, E))
    cap = 3
    dispatch, combine, _ = top_k_routing(logits, cap, top_k=2)
    # Each (expert, slot) pair serves at most one token.
    per_slot = np.asarray(dispatch).sum(axis=0)
    assert per_slot.max() <= 1.0
    # Each token occupies at most top_k slots and combine mass is
    # normalized over its kept experts.
    per_token = np.asarray(dispatch).sum(axis=(1, 2))
    assert per_token.max() <= 2.0
    mass = np.asarray(combine).sum(axis=(1, 2))
    assert mass.max() <= 1.0 + 1e-5


def test_routing_uniform_aux_is_one():
    # Perfectly uniform router -> load-balance loss at its minimum 1.
    logits = jnp.zeros((64, E))
    _, _, aux = top_k_routing(logits, capacity=64, top_k=1)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


def test_expert_capacity_bounds():
    assert expert_capacity(64, 4, 1.0, 1) == 16
    assert expert_capacity(64, 4, 1.25, 2) == 40
    assert expert_capacity(1, 64, 1.0, 1) == 1  # never zero


# -- expert-parallel vs dense reference -------------------------------


@pytest.mark.parametrize("expert_par", [2, 4])
@pytest.mark.parametrize("top_k", [1, 2])
def test_expert_parallel_matches_dense(weights, expert_par, top_k):
    tokens, gate_w, w_in, w_out = weights
    mesh = build_expert_mesh(expert=expert_par)
    # Ample capacity -> no drops -> exact agreement with the
    # single-group dense reference.
    kwargs = dict(capacity_factor=float(E), top_k=top_k)
    want, _ = dense_moe(tokens, gate_w, w_in, w_out, **kwargs)
    got, aux_got = expert_parallel_moe(mesh, tokens, gate_w, w_in,
                                       w_out, **kwargs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # aux is a group-local statistic (mean over device groups, not
    # the global-batch value), so only its bounds are portable:
    # >= 1 by the rearrangement inequality, finite always.
    assert np.isfinite(float(aux_got)) and float(aux_got) >= 1.0 - 1e-5


def test_expert_count_must_divide_axis(weights):
    tokens, gate_w, w_in, w_out = weights
    mesh = build_expert_mesh(expert=8)
    with pytest.raises(ValueError, match="not divisible"):
        expert_parallel_moe(mesh, tokens, gate_w, w_in[:6], w_out[:6])


def test_expert_parallel_grads_flow(weights):
    tokens, gate_w, w_in, w_out = weights
    mesh = build_expert_mesh(expert=4)

    def loss(w_in):
        out, aux = expert_parallel_moe(
            mesh, tokens, gate_w, w_in, w_out, capacity_factor=2.0)
        return jnp.mean(out ** 2) + 0.01 * aux

    grads = jax.grad(loss)(w_in)
    assert grads.shape == w_in.shape
    assert np.isfinite(np.asarray(grads)).all()
    assert float(jnp.abs(grads).sum()) > 0.0


# -- module + model ---------------------------------------------------


def test_moe_mlp_module_parallel_matches_local():
    mesh = build_expert_mesh(expert=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, D),
                          jnp.float32)
    kwargs = dict(num_experts=E, mlp_ratio=2, capacity_factor=float(E),
                  dtype=jnp.float32)
    local = MoEMlp(**kwargs)
    par = MoEMlp(mesh=mesh, **kwargs)
    variables = local.init(jax.random.PRNGKey(2), x)
    want, _ = local.apply(variables, x)
    got, _ = par.apply(variables, x)  # same weights, different wiring
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_moe_lm_forward_shapes():
    model = MoETransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                             num_heads=4, num_experts=E,
                             max_seq_len=64, dtype=jnp.float32)
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits, aux = model.apply(variables, tokens)
    assert logits.shape == (2, 16, 64)
    assert logits.dtype == jnp.float32
    assert np.isfinite(float(aux))


def test_moe_lm_trains_expert_parallel():
    """One real Trainer step over a ("data", "expert") mesh: expert
    kernels sharded over the expert axis, batch over data, router
    loss folded into the LM objective."""
    mesh = build_expert_mesh(expert=4, data=2)
    model = MoETransformerLM(vocab_size=64, embed_dim=32, num_layers=2,
                             num_heads=4, num_experts=E,
                             max_seq_len=64, dtype=jnp.float32,
                             mesh=mesh)
    trainer = Trainer(
        make_apply_fn(model),
        with_router_loss(next_token_loss_fn(mean_cross_entropy_loss)),
        optax.adam(1e-3), mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    variables = model.init(jax.random.PRNGKey(1), tokens)
    state = trainer.init_state(variables)

    # The stacked expert kernels landed on the expert axis.
    w_in = state.params["block1"]["moe"]["w_in"]
    spec = w_in.sharding.spec
    assert spec[0] == EXPERT_AXIS

    batch = jax.device_put((tokens, tokens),
                           (batch_sharding(mesh),) * 2)
    state, loss = trainer.train_step(state, batch)
    state, loss2 = trainer.train_step(state, batch)
    assert np.isfinite(float(loss)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss)  # it learns
    assert int(state.step) == 2
