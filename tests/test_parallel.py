# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Mesh/sharding/trainer tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from container_engine_accelerators_tpu.models import MnistMLP, resnet
from container_engine_accelerators_tpu.models import mlp as mlp_mod
from container_engine_accelerators_tpu.models.resnet import (
    make_apply_fn as resnet_apply_fn,
)
from container_engine_accelerators_tpu.parallel import (
    MeshSpec,
    Trainer,
    batch_sharding,
    build_mesh,
    chips_from_env,
    param_shardings,
)
from container_engine_accelerators_tpu.parallel.data import SyntheticLoader
from container_engine_accelerators_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
)
from container_engine_accelerators_tpu.parallel.train import (
    cross_entropy_loss,
)

# Tier-1 budget: this module compiles many distinct XLA programs and
# runs minutes on the CI CPU mesh. It only became collectable when the
# shard_map compat shim fixed the jax-version import error, and
# including it would blow the 870s tier-1 cap — so it runs in the full
# lane (`make test` / pytest without `-m "not slow"`) instead.
pytestmark = pytest.mark.slow



def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_build_mesh_default_pure_dp():
    mesh = build_mesh()
    assert mesh.shape[DATA_AXIS] == 8
    assert mesh.shape[MODEL_AXIS] == 1


def test_build_mesh_dp_tp():
    mesh = build_mesh(MeshSpec(data=4, model=2))
    assert mesh.shape[DATA_AXIS] == 4
    assert mesh.shape[MODEL_AXIS] == 2


def test_build_mesh_oversubscribed():
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(data=3, model=3))  # 9 > 8 visible


def test_build_mesh_rejects_nonpositive_factors():
    for data, model in ((0, 2), (-2, 2), (2, 0), (2, -2)):
        with pytest.raises(ValueError):
            build_mesh(MeshSpec(data=data, model=model))


def test_build_mesh_explicit_submesh():
    # Explicit factors may use a leading subset of the visible
    # devices (e.g. a 2x2 dp x pp grid on an 8-chip host).
    mesh = build_mesh(MeshSpec(data=3, model=2))
    assert mesh.devices.shape == (3, 2)


def test_chips_from_env(monkeypatch):
    monkeypatch.setenv("TPU_VISIBLE_DEVICES", "0,1,4,5")
    assert chips_from_env() == [0, 1, 4, 5]
    monkeypatch.setenv("TPU_VISIBLE_DEVICES", "")
    assert chips_from_env() is None
    monkeypatch.setenv("TPU_VISIBLE_DEVICES", "a,b")
    assert chips_from_env() is None


def test_param_shardings_shard_wide_kernels():
    mesh = build_mesh(MeshSpec(data=4, model=2))
    params = {
        "dense": {"kernel": jnp.zeros((256, 1024)),
                  "bias": jnp.zeros((1024,))},
        "small": {"kernel": jnp.zeros((16, 16))},
    }
    shardings = param_shardings(mesh, params)
    assert shardings["dense"]["kernel"].spec == \
        jax.sharding.PartitionSpec(None, MODEL_AXIS)
    assert shardings["dense"]["bias"].spec == jax.sharding.PartitionSpec()
    assert shardings["small"]["kernel"].spec == jax.sharding.PartitionSpec()


def test_param_shardings_expert_kernels_pin_layout():
    """On a hypothetical expert×model(×fsdp) mesh, matched expert
    kernels keep exactly P(expert, None, None): the model-parallel
    and FSDP branches must NOT add feature-dim axes, because
    expert_parallel_moe was only ever validated against per-expert
    kernels that are whole within an expert shard (ADVICE r3)."""
    from container_engine_accelerators_tpu.parallel.expert import (
        EXPERT_AXIS,
    )

    devices = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = jax.sharding.Mesh(
        devices, (DATA_AXIS, EXPERT_AXIS, MODEL_AXIS))
    params = {
        "moe": {"w_in": jnp.zeros((4, 64, 1024)),
                "w_out": jnp.zeros((4, 1024, 64))},
        "dense": {"kernel": jnp.zeros((256, 1024))},
    }
    for fsdp in (False, True):
        shardings = param_shardings(mesh, params, fsdp=fsdp)
        assert shardings["moe"]["w_in"].spec == \
            jax.sharding.PartitionSpec(EXPERT_AXIS, None, None)
        assert shardings["moe"]["w_out"].spec == \
            jax.sharding.PartitionSpec(EXPERT_AXIS, None, None)
    # Non-expert params on the same mesh still pick up model (and
    # FSDP data) sharding as usual.
    shardings = param_shardings(mesh, params, fsdp=True)
    assert shardings["dense"]["kernel"].spec == \
        jax.sharding.PartitionSpec(None, MODEL_AXIS)


def _train_mlp(mesh, steps=30):
    model = MnistMLP(hidden=1024, dtype=jnp.float32)
    apply_fn = mlp_mod.make_apply_fn(model)
    trainer = Trainer(apply_fn, cross_entropy_loss,
                      optax.sgd(0.1, momentum=0.9), mesh=mesh)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    state = trainer.init_state(variables)
    loader = SyntheticLoader(64, (28, 28, 1), 10,
                             sharding=batch_sharding(mesh), pool=1)
    losses = []
    for _, batch in zip(range(steps), loader):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    return losses


def test_trainer_dp_loss_decreases():
    losses = _train_mlp(build_mesh())
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]


def test_trainer_dp_tp_matches_dp():
    """Same data, same init: dp and dp x tp runs must agree closely —
    the sharding layout must not change the math."""
    dp = _train_mlp(build_mesh(), steps=5)
    dptp = _train_mlp(build_mesh(MeshSpec(data=4, model=2)), steps=5)
    np.testing.assert_allclose(dp, dptp, rtol=2e-4)


def test_trainer_resnet_step_runs_sharded():
    mesh = build_mesh(MeshSpec(data=4, model=2))
    model = resnet(depth=18, num_classes=8, dtype=jnp.float32, width=8)
    trainer = Trainer(resnet_apply_fn(model), cross_entropy_loss,
                      optax.sgd(0.01), mesh=mesh)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    state = trainer.init_state(variables)
    loader = SyntheticLoader(16, (32, 32, 3), 8,
                             sharding=batch_sharding(mesh), pool=1)
    batch = next(loader)
    state, loss1 = trainer.train_step(state, batch)
    state, loss2 = trainer.train_step(state, batch)
    assert float(loss2) < float(loss1)
    assert int(state.step) == 2


def test_train_driver_checkpoint_resume(tmp_path):
    """Checkpoint/resume through the demo training driver (the aux
    subsystem the reference delegates to --model_dir, SURVEY.md s5)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "demo_train", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    args = ["--model", "mnist", "--steps", "3", "--warmup-steps", "0",
            "--batch-size", "16", "--model-dir", str(tmp_path)]
    result1 = mod.main(args)
    assert result1["final_loss"] is not None
    import os
    assert any(n.startswith("checkpoint_") for n in os.listdir(tmp_path))
    # Second run resumes from step 3 and checkpoints at step 6.
    mod.main(args)
    assert any(n == "checkpoint_6" for n in os.listdir(tmp_path))


def test_grad_accum_matches_full_batch():
    """One grad_accum=4 step equals one full-batch step: equal-size
    microbatch chunks make the accumulated mean gradient exactly the
    full-batch mean (up to fp reassociation)."""
    import optax

    from container_engine_accelerators_tpu.parallel.train import Trainer

    def apply_fn(variables, x, train):
        w = variables["params"]["w"]
        return jnp.tanh(x @ w), {}

    def loss_fn(logits, labels):
        onehot = jax.nn.one_hot(labels, logits.shape[-1])
        return -jnp.mean(jnp.sum(
            onehot * jax.nn.log_softmax(logits.astype(jnp.float32)),
            axis=-1))

    mesh = build_mesh(MeshSpec(data=8))
    variables = {"params": {"w": jax.random.normal(
        jax.random.PRNGKey(0), (16, 4), jnp.float32) * 0.3}}
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 4)

    results = {}
    for accum in (1, 4):
        tr = Trainer(apply_fn, loss_fn, optax.sgd(0.5), mesh=mesh,
                     grad_accum=accum)
        state = tr.init_state(variables)
        state, loss = tr.train_step(state, (x, y))
        results[accum] = (np.asarray(state.params["w"]), float(loss))
    np.testing.assert_allclose(results[1][0], results[4][0],
                               rtol=1e-6, atol=1e-6)
    assert abs(results[1][1] - results[4][1]) < 1e-5


def test_grad_accum_distinct_step_per_microbatch():
    """Step-keyed apply_fns (dropout) must see a distinct virtual
    step per chunk — reusing one step would reuse one dropout mask
    across all microbatches. The probe returns logits == step, so
    the accumulated loss is the mean of the per-chunk steps."""
    import optax

    from container_engine_accelerators_tpu.parallel.train import Trainer

    def apply_fn(variables, x, train, step):
        del variables
        return jnp.full(x.shape[:1], step, jnp.float32), {}

    tr = Trainer(apply_fn, lambda lo, la: jnp.mean(lo), optax.sgd(0.0),
                 mesh=build_mesh(MeshSpec(data=8)), grad_accum=4)
    state = tr.init_state(
        {"params": {"w": jnp.zeros((1,), jnp.float32)}})
    x = jnp.zeros((32, 2))
    _, loss = tr.train_step(state, (x, jnp.zeros((32,))))
    # state.step=0, accum=4 -> virtual steps 0,1,2,3 -> mean 1.5.
    assert float(loss) == 1.5


def test_grad_accum_rejects_indivisible_batch():
    import optax

    from container_engine_accelerators_tpu.parallel.train import Trainer

    def apply_fn(variables, x, train):
        return x @ variables["params"]["w"], {}

    tr = Trainer(apply_fn, lambda lo, la: jnp.mean(lo), optax.sgd(0.1),
                 mesh=build_mesh(MeshSpec(data=8)), grad_accum=3)
    state = tr.init_state(
        {"params": {"w": jnp.zeros((4, 2), jnp.float32)}})
    with pytest.raises(ValueError, match="not divisible"):
        tr.train_step(state, (jnp.zeros((16, 4)), jnp.zeros((16,))))
    with pytest.raises(ValueError):
        Trainer(apply_fn, lambda lo, la: jnp.mean(lo), optax.sgd(0.1),
                grad_accum=0)


def test_train_driver_async_periodic_checkpoints(tmp_path):
    """--checkpoint-every saves run async (overlapping later steps);
    every periodic checkpoint must still be fully written and
    readable once main() returns — verified by reading the archive
    files directly, independent of the library's own reader."""
    import importlib.util
    import json
    import os

    spec = importlib.util.spec_from_file_location(
        "demo_train_async_ckpt", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main(["--model", "mnist", "--steps", "3", "--warmup-steps", "0",
              "--batch-size", "16", "--model-dir", str(tmp_path),
              "--checkpoint-every", "1"])
    names = sorted(n for n in os.listdir(tmp_path)
                   if n.startswith("checkpoint_"))
    assert names == ["checkpoint_1", "checkpoint_2", "checkpoint_3"]
    for name in names:
        meta = json.loads((tmp_path / name / "meta.json").read_text())
        assert meta["step"] == int(name.rsplit("_", 1)[1])
        with np.load(tmp_path / name / "arrays.npz") as arc:
            assert int(arc["['step']"]) == meta["step"]
            assert meta["leaf_count"] == len(arc.files)
            assert any("['params']" in k for k in arc.files)
            assert any("['opt_state']" in k for k in arc.files)


def test_train_driver_checkpoint_retention(tmp_path):
    """--keep-checkpoints prunes old finished checkpoints; the final
    (newest) one survives and restores."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "demo_train_retention", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main(["--model", "mnist", "--steps", "4", "--warmup-steps", "0",
              "--batch-size", "16", "--model-dir", str(tmp_path),
              "--checkpoint-every", "1", "--keep-checkpoints", "2"])
    names = sorted(n for n in os.listdir(tmp_path)
                   if n.startswith("checkpoint_"))
    assert names == ["checkpoint_3", "checkpoint_4"]
    # Non-integer suffixes (in-flight .tmp-* write dirs) and
    # integer-named dirs without a finished meta.json are ignored by
    # listing, pruning, and restore.
    (tmp_path / "checkpoint_9.tmp-123-0").mkdir()
    (tmp_path / "checkpoint_8").mkdir()  # no meta.json: unfinished
    assert mod._list_checkpoints(str(tmp_path)) == [
        (3, "checkpoint_3"), (4, "checkpoint_4")]


def test_train_driver_moe_expert_parallel():
    """The LM demo path end-to-end: MoE model, expert mesh axis,
    router loss, token loader — through the same CLI surface the
    K8s job manifests invoke."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "demo_train_moe", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    result = mod.main([
        "--model", "moe", "--seq-len", "32", "--vocab-size", "64",
        "--embed-dim", "32", "--num-layers", "2", "--num-heads", "4",
        "--num-experts", "4", "--expert-parallelism", "4",
        "--batch-size", "8", "--steps", "3", "--warmup-steps", "1"])
    assert result["final_loss"] is not None
    assert result["tokens_per_sec"] > 0


def test_build_hybrid_mesh_layout():
    """DCN-granule mesh: model groups never cross a granule, data
    rows enumerate granule-local groups first."""
    from container_engine_accelerators_tpu.parallel import (
        build_hybrid_mesh,
    )
    devices = jax.devices()
    mesh = build_hybrid_mesh(model=2, num_granules=2)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    grid = mesh.devices
    granule = {d.id: (0 if d.id < 4 else 1) for d in devices}
    for row in grid:
        # tensor-parallel peers share a granule (ICI, not DCN)
        assert len({granule[d.id] for d in row}) == 1
    # first half of the data axis is granule 0, second half granule 1
    assert [granule[row[0].id] for row in grid] == [0, 0, 1, 1]


def test_build_hybrid_mesh_trains():
    from container_engine_accelerators_tpu.parallel import (
        build_hybrid_mesh,
    )
    import optax
    from container_engine_accelerators_tpu.models import MnistMLP
    from container_engine_accelerators_tpu.models import mlp as mlp_mod
    from container_engine_accelerators_tpu.parallel.train import (
        cross_entropy_loss,
    )

    mesh = build_hybrid_mesh(model=2, num_granules=2)
    model = MnistMLP(hidden=32, dtype=jnp.float32)
    trainer = Trainer(mlp_mod.make_apply_fn(model), cross_entropy_loss,
                      optax.sgd(0.1), mesh=mesh)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 28, 28, 1)))
    state = trainer.init_state(variables)
    loader = SyntheticLoader(16, (28, 28, 1), 10,
                             sharding=batch_sharding(mesh), pool=1)
    state, loss = trainer.train_step(state, next(loader))
    assert np.isfinite(float(loss))


def test_build_hybrid_mesh_validation():
    from container_engine_accelerators_tpu.parallel import (
        build_hybrid_mesh,
    )
    with pytest.raises(ValueError, match="num_granules"):
        build_hybrid_mesh(model=2)  # single process, no split given
    with pytest.raises(ValueError, match="cannot span DCN"):
        build_hybrid_mesh(model=8, num_granules=2)


def test_train_driver_context_parallel_ring():
    """Long-context LM path end-to-end: ring attention over a
    ("data", "context") mesh through the demo CLI."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "demo_train_ring", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    result = mod.main([
        "--model", "transformer", "--attention", "ring",
        "--context-parallelism", "4", "--seq-len", "32",
        "--vocab-size", "64", "--embed-dim", "32", "--num-layers", "2",
        "--num-heads", "4", "--batch-size", "8", "--steps", "3",
        "--warmup-steps", "1"])
    assert result["final_loss"] is not None
    assert result["tokens_per_sec"] > 0


def test_checkpoint_portable_across_meshes(tmp_path):
    """Checkpoints are parallelism-agnostic: a run trained pure-dp
    resumes under dp x tp (the driver restores into whatever
    shardings the new mesh dictates)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "demo_train_xmesh", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    base = ["--model", "mnist", "--steps", "3", "--warmup-steps", "0",
            "--batch-size", "16", "--model-dir", str(tmp_path)]
    mod.main(base + ["--model-parallelism", "1"])
    import os
    assert any(n == "checkpoint_3" for n in os.listdir(tmp_path))
    # Resume the same checkpoint under a 4x2 (data, model) mesh.
    result = mod.main(base + ["--model-parallelism", "2"])
    assert any(n == "checkpoint_6" for n in os.listdir(tmp_path))
    assert result["final_loss"] is not None


def test_device_side_augmentation():
    """ops.augment: shape/dtype preserved, crop stays in bounds,
    determinism per key, and the Trainer hook trains."""
    import optax

    from container_engine_accelerators_tpu.models import MnistMLP
    mlp_apply_fn = mlp_mod.make_apply_fn
    from container_engine_accelerators_tpu.ops.augment import (
        make_augment_fn,
        random_crop,
        random_flip,
    )
    from container_engine_accelerators_tpu.parallel.train import (
        cross_entropy_loss,
    )

    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (8, 12, 12, 3))
    out = random_crop(rng, images, 2)
    assert out.shape == images.shape and out.dtype == images.dtype
    flipped = random_flip(rng, images)
    # Every row is either identical or exactly mirrored.
    same = np.isclose(np.asarray(flipped), np.asarray(images)).all(
        axis=(1, 2, 3))
    mirrored = np.isclose(np.asarray(flipped),
                          np.asarray(images[:, :, ::-1, :])).all(
        axis=(1, 2, 3))
    assert (same | mirrored).all()
    fn = make_augment_fn(flip=True, crop_padding=2)
    np.testing.assert_array_equal(np.asarray(fn(rng, images)),
                                  np.asarray(fn(rng, images)))
    assert make_augment_fn(flip=False, crop_padding=0) is None

    model = MnistMLP()
    mesh = build_mesh()
    trainer = Trainer(mlp_apply_fn(model), cross_entropy_loss,
                      optax.sgd(0.1), mesh=mesh, augment_fn=fn)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 12, 12, 3)), train=False)
    state = trainer.init_state(variables)
    batch = (images, jnp.zeros((8,), jnp.int32))
    state, loss0 = trainer.train_step(state, batch)
    state, loss1 = trainer.train_step(state, batch)
    assert np.isfinite(float(loss0)) and np.isfinite(float(loss1))


def test_label_smoothing_paths_agree():
    """Pallas-kernel smoothing (layered logsumexp-mean term) must
    equal the lax one-hot formulation, and epsilon=0 must be exactly
    the hard loss."""
    from container_engine_accelerators_tpu.ops import (
        mean_cross_entropy_loss,
    )
    from container_engine_accelerators_tpu.parallel.train import (
        cross_entropy_loss,
    )

    logits = jax.random.normal(jax.random.PRNGKey(0), (16, 32))
    labels = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 32)
    for eps in (0.0, 0.1, 0.3):
        a = float(mean_cross_entropy_loss(logits, labels,
                                          label_smoothing=eps))
        b = float(cross_entropy_loss(logits, labels,
                                     label_smoothing=eps))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    hard = float(mean_cross_entropy_loss(logits, labels))
    np.testing.assert_allclose(
        hard, float(mean_cross_entropy_loss(logits, labels,
                                            label_smoothing=0.0)))
    with pytest.raises(ValueError, match="label_smoothing"):
        mean_cross_entropy_loss(logits, labels, label_smoothing=1.5)


def test_ema_shadow_params():
    """EMA tracking: shadow follows the decay recursion exactly,
    eval reads the shadow, ensure_ema seeds a restored state, and
    ema off leaves the state shape untouched."""
    import dataclasses

    import optax

    from container_engine_accelerators_tpu.parallel.train import (
        cross_entropy_loss,
    )

    model = MnistMLP(hidden=16, dtype=jnp.float32)
    apply_fn = mlp_mod.make_apply_fn(model)
    mesh = build_mesh()
    decay = 0.9
    trainer = Trainer(apply_fn, cross_entropy_loss, optax.sgd(0.1),
                      mesh=mesh, ema_decay=decay, donate_state=False)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8, 8, 1)), train=False)
    state = trainer.init_state(variables)
    assert state.ema_params is not None

    batch = (jax.random.normal(jax.random.PRNGKey(1), (8, 8, 8, 1)),
             jnp.zeros((8,), jnp.int32))
    expect = jax.tree_util.tree_map(lambda p: np.asarray(p),
                                    state.params)
    s = state
    for _ in range(3):
        prev = jax.tree_util.tree_map(np.asarray, s.params)
        s, _ = trainer.train_step(s, batch)
        expect = jax.tree_util.tree_map(
            lambda e, p: e * decay + np.asarray(p) * (1 - decay),
            expect, s.params)
    for got, want in zip(jax.tree_util.tree_leaves(s.ema_params),
                         jax.tree_util.tree_leaves(expect)):
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-6)

    # eval reads the shadow
    images = batch[0]
    logits = trainer.eval_step(s, images)
    want_logits, _ = apply_fn({"params": s.ema_params}, images, False)
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(want_logits), rtol=1e-5,
                               atol=1e-5)

    # ensure_ema seeds a shadow-less state (old checkpoint restore)
    bare = dataclasses.replace(s, ema_params=None)
    seeded = trainer.ensure_ema(bare)
    for a, b in zip(jax.tree_util.tree_leaves(seeded.ema_params),
                    jax.tree_util.tree_leaves(bare.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # off: no shadow anywhere
    t2 = Trainer(apply_fn, cross_entropy_loss, optax.sgd(0.1),
                 mesh=mesh)
    s2 = t2.init_state(variables)
    assert s2.ema_params is None
    assert t2.eval_params(s2) is s2.params
    with pytest.raises(ValueError, match="ema_decay"):
        Trainer(apply_fn, cross_entropy_loss, optax.sgd(0.1),
                mesh=mesh, ema_decay=1.0)


def test_fsdp_shards_params_and_matches_dp():
    """fsdp=True: big kernels and their optimizer moments shard a dim
    over the data axis (per-device residency drops), while the loss
    trajectory matches pure DP (same math, different layout)."""
    mesh = build_mesh(MeshSpec(data=8, model=1))
    model = resnet(depth=18, num_classes=8, dtype=jnp.float32, width=64)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    images = jax.random.normal(jax.random.PRNGKey(1), (16, 32, 32, 3))
    labels = jax.random.randint(jax.random.PRNGKey(2), (16,), 0, 8)

    losses = {}
    for fsdp in (False, True):
        trainer = Trainer(resnet_apply_fn(model), cross_entropy_loss,
                          optax.sgd(0.1, momentum=0.9), mesh=mesh,
                          fsdp=fsdp)
        state = trainer.init_state(variables)
        batch = (jax.device_put(images, batch_sharding(mesh)),
                 jax.device_put(labels, batch_sharding(mesh)))
        for _ in range(2):
            state, loss = trainer.train_step(state, batch)
        losses[fsdp] = float(loss)

        leaves = jax.tree_util.tree_leaves_with_path(state.params)
        wide = [(path, leaf) for path, leaf in leaves
                if len(leaf.shape) >= 2
                and any(dim >= 512 and dim % 8 == 0
                        for dim in leaf.shape)]
        assert wide, "model has no fsdp-eligible kernels"
        for path, leaf in wide:
            spec = leaf.sharding.spec
            if fsdp:
                assert DATA_AXIS in spec, (path, spec, leaf.shape)
                # Per-device shard really is smaller than the param.
                shard = leaf.addressable_shards[0].data
                assert shard.size == leaf.size // 8, (path, leaf.shape)
            else:
                assert DATA_AXIS not in tuple(spec), (path, spec)
        # 1-D params (BatchNorm scales/biases) must stay replicated
        # even when 512-wide: gathering them every step costs more
        # than the bytes saved.
        for path, leaf in leaves:
            if len(leaf.shape) < 2:
                assert DATA_AXIS not in tuple(leaf.sharding.spec), path
        # Optimizer moments mirror the parameter layout.
        momentum = jax.tree_util.tree_leaves(state.opt_state)
        if fsdp:
            assert any(
                DATA_AXIS in getattr(m.sharding, "spec", ())
                for m in momentum if hasattr(m, "sharding")
                and m.size > 1)

    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)


def test_fsdp_composes_with_tensor_parallelism():
    """2D layout: out-features over "model", another dim over "data"
    — both axes appear in one wide kernel's spec."""
    mesh = build_mesh(MeshSpec(data=4, model=2))
    model = resnet(depth=18, num_classes=8, dtype=jnp.float32,
                   width=128)
    trainer = Trainer(resnet_apply_fn(model), cross_entropy_loss,
                      optax.sgd(0.1), mesh=mesh, fsdp=True)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    state = trainer.init_state(variables)
    specs = [tuple(leaf.sharding.spec) for leaf in
             jax.tree_util.tree_leaves(state.params)]
    assert any(MODEL_AXIS in s and DATA_AXIS in s for s in specs), (
        "no kernel carries both axes")


def test_train_driver_pipeline_parallelism(tmp_path):
    """--pipeline-parallelism K trains the PipelinedLM over a
    (data, pipe) mesh through the demo CLI, learns, and
    checkpoint/resumes its own payload shape."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "demo_train_pp", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    args = ["--model", "transformer", "--pipeline-parallelism", "4",
            "--num-layers", "8", "--embed-dim", "32",
            "--num-heads", "4", "--vocab-size", "64",
            "--seq-len", "16", "--batch-size", "8",
            "--num-microbatches", "2", "--steps", "3",
            "--warmup-steps", "1", "--model-dir", str(tmp_path)]
    result = mod.main(args)
    assert result["pipeline_parallelism"] == 4
    assert result["final_loss"] is not None
    import os
    assert any(n == "checkpoint_3" for n in os.listdir(tmp_path))
    # Resume picks up the newest payload and re-checkpoints at 6.
    mod.main(args)
    assert any(n == "checkpoint_6" for n in os.listdir(tmp_path))
    # Incompatible flags are rejected loudly, not half-applied.
    import pytest as _pytest
    with _pytest.raises(SystemExit, match="fsdp"):
        mod.main(args + ["--fsdp"])


def test_train_driver_grad_clip_and_seed():
    """--grad-clip bounds the raw gradient's global norm inside the
    shared optimizer chain, and --seed changes the init stream
    (different final loss for a fixed data stream)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "demo_train_clip", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    base = ["--model", "mnist", "--steps", "2", "--warmup-steps", "0",
            "--batch-size", "16"]
    r_clip = mod.main(base + ["--grad-clip", "1e-8"])
    r_free = mod.main(base)
    # A vanishing clip norm freezes learning: the unclipped run must
    # end at a strictly lower loss than the frozen one.
    assert r_free["final_loss"] < r_clip["final_loss"]
    r_seed = mod.main(base + ["--seed", "7"])
    assert r_seed["final_loss"] != r_free["final_loss"]
