# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Property-based checks over the scheduler-critical allocation path.

The example-driven tests pin known-good cases; these sweep randomized
(topology, availability, must-include, size) points and assert the
invariants that kubelet correctness depends on:

  * preferred_allocation returns exactly `size` devices drawn from
    `available`, containing `must_include`;
  * when the whole node is free and the size factors into the
    topology, the choice is a contiguous box (bounding-box volume ==
    size) — the minimal-hop guarantee;
  * topology_envs reports TPU_CHIPS_PER_PROCESS_BOUNDS exactly when
    the chip set fills its bounding box, and TPU_VISIBLE_DEVICES
    always matches the chips handed out.
"""

import numpy as np
import pytest

from container_engine_accelerators_tpu.chip import PyChipBackend
from container_engine_accelerators_tpu.plugin import config as cfg
from container_engine_accelerators_tpu.plugin.envs import (
    chips_form_box,
    topology_envs,
)
from container_engine_accelerators_tpu.plugin.manager import TpuManager

TOPOLOGIES = ["2x2", "2x4", "4x4", "2x2x2", "4x4x2"]


def _node(fake_node, topo, partition=""):
    dims = [int(d) for d in topo.split("x")]
    while len(dims) < 3:
        dims.append(1)
    n = dims[0] * dims[1] * dims[2]
    for i in range(n):
        fake_node.add_chip(i)
    fake_node.set_topology(topo)
    mgr = TpuManager(dev_dir=fake_node.dev_dir,
                     state_dir=fake_node.state_dir,
                     backend=PyChipBackend(),
                     tpu_config=cfg.TpuConfig(
                         tpu_partition_size=partition))
    mgr.start()
    return mgr, n


def _bounding_volume(coords):
    spans = [max(c[i] for c in coords) - min(c[i] for c in coords) + 1
             for i in range(3)]
    return spans[0] * spans[1] * spans[2]


def test_preferred_allocation_invariants(fake_node):
    rng = np.random.default_rng(0)
    topo = "4x4"
    mgr, n = _node(fake_node, topo)
    all_devs = [f"accel{i}" for i in range(n)]
    for _ in range(150):
        n_avail = int(rng.integers(1, n + 1))
        available = sorted(
            rng.choice(all_devs, size=n_avail, replace=False).tolist())
        size = int(rng.integers(1, n_avail + 1))
        n_must = int(rng.integers(0, size + 1))
        must = sorted(
            rng.choice(available, size=n_must, replace=False).tolist())
        chosen = mgr.preferred_allocation(available, must, size)
        assert len(chosen) == size, (available, must, size, chosen)
        assert len(set(chosen)) == size
        assert set(chosen) <= set(available)
        assert set(must) <= set(chosen)


def test_preferred_allocation_full_node_is_contiguous(fake_node):
    """With the whole node free, any size that factors into the
    topology must come back as a contiguous box."""
    mgr, n = _node(fake_node, "4x4")
    all_devs = [f"accel{i}" for i in range(n)]
    backend = mgr._backend
    for size in (1, 2, 4, 8, 16):
        chosen = mgr.preferred_allocation(all_devs, [], size)
        coords = [backend.chip_coords(int(d[5:])) for d in chosen]
        assert _bounding_volume(coords) == size, (size, chosen)


def test_subslice_solver_invariants(fake_node):
    """Every uniform tiling partitions the chips exactly (each chip
    in one contiguous subslice); every non-tiling shape raises."""
    from container_engine_accelerators_tpu.chip.backend import (
        NonUniformPartitionError,
    )

    mgr, n = _node(fake_node, "4x4x2")
    backend = mgr._backend
    shapes = ["1x1", "2x1", "1x2", "2x2", "4x1", "4x4", "2x2x2",
              "4x4x2", "1x1x2", "3x1", "2x3", "4x3x2", "5x1"]
    for shape in shapes:
        dims = [int(d) for d in shape.split("x")]
        while len(dims) < 3:
            dims.append(1)
        tiles = all(t % s == 0 for t, s in zip((4, 4, 2), dims))
        if not tiles:
            try:
                backend.subslice_count(shape)
            except NonUniformPartitionError:
                continue
            raise AssertionError(f"{shape} should not tile 4x4x2")
        count = backend.subslice_count(shape)
        vol = dims[0] * dims[1] * dims[2]
        assert count == n // vol, (shape, count)
        seen = []
        for i in range(count):
            chips = backend.subslice_chips(shape, i)
            assert len(chips) == vol
            coords = [backend.chip_coords(c) for c in chips]
            assert _bounding_volume(coords) == vol, (shape, i, chips)
            seen.extend(chips)
        assert sorted(seen) == list(range(n)), shape  # exact partition


@pytest.mark.parametrize("partition", ["1x2", "2x2"])
def test_gang_allocation_invariants(fake_node, partition):
    """The Flex-MIG gang path: every returned gang is chip-disjoint,
    drawn from `available`, honors `must_include`, and is exactly
    `size` slices; ties and scoring are deterministic (same request
    -> same answer, across fresh managers)."""
    rng = np.random.default_rng(7)
    mgr, n = _node(fake_node, "4x4", partition=partition)
    all_slices = sorted(mgr.list_devices())
    for _ in range(60):
        n_avail = int(rng.integers(1, len(all_slices) + 1))
        available = sorted(rng.choice(
            all_slices, size=n_avail, replace=False).tolist())
        size = int(rng.integers(1, n_avail + 1))
        n_must = int(rng.integers(0, size + 1))
        must = sorted(rng.choice(
            available, size=n_must, replace=False).tolist())
        gang = mgr.preferred_allocation(available, must, size)
        assert len(gang) == size, (available, must, size, gang)
        assert len(set(gang)) == size
        assert set(gang) <= set(available)
        assert set(must) <= set(gang)
        chips = [c for d in gang for c in mgr.device_chips(d)]
        assert len(chips) == len(set(chips)), "gang not chip-disjoint"
        # Determinism: the same request must produce the same gang.
        assert mgr.preferred_allocation(available, must, size) == gang


@pytest.mark.parametrize("partition,size", [
    ("1x2", 2), ("1x2", 4), ("2x2", 2), ("2x2", 4), ("4x1", 2)])
def test_gang_union_is_contiguous_box(fake_node, partition, size):
    """With the whole node free and a gang size whose chip total has
    an aligned tiling, the gang's chip union must form one contiguous
    ICI box — the coherent-topology-env guarantee of gang
    allocation."""
    mgr, n = _node(fake_node, "4x4", partition=partition)
    backend = mgr._backend
    all_slices = sorted(mgr.list_devices())
    gang = mgr.preferred_allocation(all_slices, [], size)
    chips = sorted(c for d in gang for c in mgr.device_chips(d))
    coords = [backend.chip_coords(c) for c in chips]
    assert _bounding_volume(coords) == len(chips), (gang, coords)
    assert chips_form_box(coords)
    # must_include steering keeps the box property.
    pinned = all_slices[-1]
    gang2 = mgr.preferred_allocation(all_slices, [pinned], size)
    assert pinned in gang2
    coords2 = [backend.chip_coords(c) for d in gang2
               for c in mgr.device_chips(d)]
    assert _bounding_volume(coords2) == len(coords2), (gang2, coords2)


def test_gang_determinism_across_fresh_managers(fake_node):
    """Scorer ties break on the natural-sorted id tuple, so a fresh
    manager over the same node state answers identically (stable
    across runs — the kubelet may ask any plugin restart)."""
    mgr1, _ = _node(fake_node, "4x4", partition="2x2")
    available = sorted(mgr1.list_devices())
    first = [mgr1.preferred_allocation(available, [], s)
             for s in (1, 2, 3, 4)]
    mgr2 = TpuManager(dev_dir=fake_node.dev_dir,
                      state_dir=fake_node.state_dir,
                      backend=PyChipBackend(),
                      tpu_config=cfg.TpuConfig(
                          tpu_partition_size="2x2"))
    mgr2.start()
    second = [mgr2.preferred_allocation(available, [], s)
              for s in (1, 2, 3, 4)]
    assert first == second


def test_preferred_allocation_oversize_is_value_error(fake_node):
    """allocation_size above the available count must raise (mapped
    to INVALID_ARGUMENT at the gRPC surface), never silently
    truncate."""
    mgr, n = _node(fake_node, "2x2")
    with pytest.raises(ValueError, match="exceeds"):
        mgr.preferred_allocation(["accel0", "accel1"], [], 3)
    with pytest.raises(ValueError, match="must-include"):
        mgr.preferred_allocation(["accel0"], ["accel2"], 1)


def test_topology_envs_invariants(fake_node):
    rng = np.random.default_rng(1)
    mgr, n = _node(fake_node, "2x2x2")
    backend = mgr._backend
    for _ in range(100):
        k = int(rng.integers(1, n + 1))
        chips = sorted(
            rng.choice(np.arange(n), size=k, replace=False).tolist())
        coords = [backend.chip_coords(c) for c in chips]
        envs = topology_envs(chips, coords)
        assert envs["TPU_VISIBLE_DEVICES"] == ",".join(
            str(c) for c in chips)
        has_bounds = "TPU_CHIPS_PER_PROCESS_BOUNDS" in envs
        assert has_bounds == chips_form_box(coords)
        if has_bounds:
            bx, by, bz = (int(x) for x in
                          envs["TPU_CHIPS_PER_PROCESS_BOUNDS"].split(","))
            assert bx * by * bz == len(chips)
