# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Weight-only int8 serving: module exactness + checkpoint convert."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import TransformerLM
from container_engine_accelerators_tpu.models.decode import (
    greedy_decode,
)
from container_engine_accelerators_tpu.models.quantized import (
    Int8DenseGeneral,
    convert_params_int8,
    quantize_kernel_int8,
)

KW = dict(vocab_size=101, embed_dim=64, num_layers=2, num_heads=4,
          max_seq_len=32, dtype=jnp.float32)


def _native_and_quant():
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0, 101)
    native = TransformerLM(**KW)
    params = native.init(jax.random.PRNGKey(1), tokens)["params"]
    q_model = TransformerLM(weights="int8", **KW)
    template = q_model.init(jax.random.PRNGKey(1), tokens)["params"]
    return native, params, q_model, convert_params_int8(
        template, params), tokens


def test_int8_dense_matches_scaled_matmul():
    """The module computes exactly (x @ q) * s + b — the fold that
    lets the matmul run on int8 weights with no dequantized copy."""
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 16))
    m = Int8DenseGeneral(features=8, dtype=jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    w = jax.random.normal(jax.random.PRNGKey(2), (16, 8))
    q, s = quantize_kernel_int8(w)
    b = jnp.arange(8, dtype=jnp.float32)
    out = m.apply({"params": {"kernel_q": q, "scale": s, "bias": b}}, x)
    want = (x @ q.astype(jnp.float32)) * s + b
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    # and that is within quantization error of the real matmul
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w + b),
                               rtol=0.05, atol=0.05)


def test_converted_model_matches_native():
    native, params, q_model, q_params, tokens = _native_and_quant()
    l0 = native.apply({"params": params}, tokens, train=False)
    l1 = q_model.apply({"params": q_params}, tokens, train=False)
    rel = float(jnp.max(jnp.abs(l1 - l0))
                / (jnp.max(jnp.abs(l0)) + 1e-9))
    assert rel < 0.05
    # weights really are int8 (the memory claim)
    attn = q_params["block0"]["attn"]
    assert attn["qkv"]["kernel_q"].dtype == jnp.int8
    assert q_params["block0"]["Dense_0"]["kernel_q"].dtype == jnp.int8
    # full-precision islands stay full precision
    assert q_params["lm_head"]["kernel"].dtype != jnp.int8
    assert q_params["tok_embed"]["embedding"].dtype != jnp.int8


def test_quantized_decode_runs_and_mostly_agrees():
    native, params, q_model, q_params, tokens = _native_and_quant()
    want = np.asarray(greedy_decode(native, params, tokens[:, :5], 8))
    got = np.asarray(greedy_decode(q_model, q_params, tokens[:, :5], 8))
    assert got.shape == want.shape
    # quantization may flip near-ties late in generation; the prompt
    # and first generated token must agree.
    np.testing.assert_array_equal(got[:, :6], want[:, :6])


def test_convert_rejects_mismatched_tree():
    _, params, q_model, _, tokens = _native_and_quant()
    template = q_model.init(jax.random.PRNGKey(1), tokens)["params"]
    bad = dict(params)
    bad.pop("block0")
    with pytest.raises(ValueError, match="mismatch"):
        convert_params_int8(template, bad)


def test_bad_weights_value_rejected():
    model = TransformerLM(weights="int4", **KW)
    with pytest.raises(ValueError, match="weights"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
