# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Cross-process trace propagation: traceparent wire format, client
interceptor -> server interceptor over a real gRPC socket, identity
stamps, and the merged multi-process Perfetto timeline."""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap

import grpc
import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.obs.grpc_client import (
    CLIENT_RPC_HISTOGRAM,
    traced_channel,
)
from container_engine_accelerators_tpu.plugin import api
from tests.conftest import REPO_ROOT
from tests.plugin_helpers import ServingManager, short_tmpdir


@pytest.fixture(autouse=True)
def clean_tracer():
    obs.TRACER.reset()
    yield
    obs.TRACER.reset()


# -- wire format ------------------------------------------------------

def test_traceparent_round_trip():
    ctx = (0x1234abcd5678, 0x9f)
    value = obs.format_traceparent(ctx)
    assert value == ("00-000000000000000000001234abcd5678-"
                     "000000000000009f-01")
    assert obs.parse_traceparent(value) == ctx


def test_traceparent_rejects_malformed():
    for bad in ("", "junk", "00-zz-ff-01",
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero trace
                "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # zero span
                "01-" + "1" * 32 + "-" + "2" * 16):         # truncated
        assert obs.parse_traceparent(bad) is None, bad
    # Metadata without the key -> no context, never an error.
    assert obs.context_from_metadata([("other", "x")]) is None
    assert obs.context_from_metadata(None) is None


# -- the HTTP header carrier ------------------------------------------

def test_http_carrier_round_trip():
    ctx = (0xdeadbeefcafef00d, 0x1234)
    headers = obs.inject_headers(ctx, request_id="req-01.a")
    assert headers == {
        "traceparent": obs.format_traceparent(ctx),
        "x-cea-request-id": "req-01.a",
    }
    assert obs.extract_headers(headers) == (ctx, "req-01.a")


def test_http_carrier_folds_into_existing_headers():
    base = {"Content-Type": "application/json"}
    out = obs.inject_headers((1, 2), request_id="r", headers=base)
    assert out is base  # mutated in place, not replaced
    assert base["Content-Type"] == "application/json"
    assert obs.extract_headers(base) == ((1, 2), "r")


def test_http_carrier_untraced_caller_keeps_request_id():
    # No context -> no traceparent key, but the request id still
    # rides (the splice resubmit from an untraced router must bill
    # to the original request).
    headers = obs.inject_headers(None, request_id="abc")
    assert "traceparent" not in headers
    assert obs.extract_headers(headers) == (None, "abc")


def test_http_extract_malformed_or_absent_is_fresh_root():
    assert obs.extract_headers(None) == (None, None)
    assert obs.extract_headers({}) == (None, None)
    assert obs.extract_headers({"traceparent": "junk"}) \
        == (None, None)
    # Zero ids are invalid per spec; the server restarts the trace.
    assert obs.extract_headers(
        {"traceparent": "00-" + "0" * 32 + "-" + "1" * 16 + "-01"}
    ) == (None, None)


def test_http_extract_drops_hostile_request_id():
    for bad in ("", " ", "x" * 65, "a b", "a\nb", "a;rm -rf"):
        headers = {"x-cea-request-id": bad}
        assert obs.extract_headers(headers) == (None, None), bad
    # Surrounding whitespace is trimmed, not fatal.
    assert obs.extract_headers({"x-cea-request-id": " ok "}) \
        == (None, "ok")


def test_http_extract_is_case_insensitive_on_plain_dicts():
    ctx = (0xabc, 0xdef)
    headers = {"Traceparent": obs.format_traceparent(ctx),
               "X-CEA-Request-Id": "rid"}
    assert obs.extract_headers(headers) == (ctx, "rid")


def test_http_carrier_foreign_128bit_trace_id():
    # A non-cea peer's full 128-bit trace id must round-trip as
    # plain hex — never truncated to the local 64-bit id space.
    foreign = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"
    ctx, _ = obs.extract_headers({"traceparent": foreign})
    assert ctx == (0x4bf92f3577b34da6a3ce929d0e0e4736,
                   0x00f067aa0ba902b7)
    assert obs.inject_headers(ctx)["traceparent"] == foreign


def test_process_ids_are_collision_resistant():
    # Two tracers (stand-ins for two processes) must not mint
    # overlapping span ids — merged timelines rely on it.
    a, b = obs.Tracer(enabled=True), obs.Tracer(enabled=True)
    with a.span("x") as sa, b.span("y") as sb:
        assert sa.span_id != sb.span_id
        assert sa.trace_id != sb.trace_id


def _make_manager(fake_node):
    from container_engine_accelerators_tpu.chip import PyChipBackend
    from container_engine_accelerators_tpu.plugin.manager import (
        TpuManager,
    )

    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("1x2")
    mgr = TpuManager(dev_dir=fake_node.dev_dir,
                     state_dir=fake_node.state_dir,
                     backend=PyChipBackend())
    mgr.start()
    return mgr


# -- end-to-end over a real socket ------------------------------------

def test_allocate_parents_under_caller_span(fake_node):
    """The acceptance path: a span opened on the 'serving' side rides
    gRPC metadata into the plugin server, whose rpc.*Allocate span
    joins the caller's trace id and parents under the caller's span.
    (Same-process here — the subprocess version below proves the
    cross-process file story.)"""
    mgr = _make_manager(fake_node)
    with ServingManager(mgr, short_tmpdir()) as sm:
        with sm.channel() as raw:
            stub = api.DevicePluginV1Beta1Stub(traced_channel(raw))
            with obs.span("serving.request", test=True) as req:
                stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                    container_requests=[
                        api.v1beta1_pb2.ContainerAllocateRequest(
                            devicesIDs=["accel0"])]), timeout=5)
                req_ctx = req.context()
    spans = {s["name"]: s for s in obs.TRACER.snapshot()["spans"]}
    client = spans["rpc.client.v1beta1.DevicePlugin/Allocate"]
    server = spans["rpc.v1beta1.DevicePlugin/Allocate"]
    # Client span parents under the request; server span parents
    # under the CLIENT span (the injected context) — all one trace.
    assert client["trace_id"] == req_ctx[0]
    assert client["parent_id"] == req_ctx[1]
    assert server["trace_id"] == req_ctx[0]
    assert server["parent_id"] == client["span_id"]
    # Client-observed latency histogram exists for the method.
    hists = {(h.name, h.labels.get("method", ""))
             for h in obs.TRACER.histograms()}
    assert any(n == CLIENT_RPC_HISTOGRAM and m.endswith("Allocate")
               for n, m in hists)


def test_untraced_client_still_served(fake_node):
    """No metadata -> fresh trace; old clients keep working."""
    mgr = _make_manager(fake_node)
    with ServingManager(mgr, short_tmpdir()) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                container_requests=[
                    api.v1beta1_pb2.ContainerAllocateRequest(
                        devicesIDs=["accel0"])]), timeout=5)
    spans = [s for s in obs.TRACER.snapshot()["spans"]
             if s["name"] == "rpc.v1beta1.DevicePlugin/Allocate"]
    assert spans and spans[0]["parent_id"] is None


def test_failed_rpc_closes_client_span_as_error(fake_node):
    mgr = _make_manager(fake_node)
    with ServingManager(mgr, short_tmpdir()) as sm:
        with sm.channel() as raw:
            stub = api.DevicePluginV1Beta1Stub(traced_channel(raw))
            with pytest.raises(grpc.RpcError):
                stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                    container_requests=[
                        api.v1beta1_pb2.ContainerAllocateRequest(
                            devicesIDs=["nope"])]), timeout=5)
    spans = {s["name"]: s for s in obs.TRACER.snapshot()["spans"]}
    client = spans["rpc.client.v1beta1.DevicePlugin/Allocate"]
    assert client["status"] == "error"
    assert not obs.TRACER.snapshot()["open_spans"]


def test_serving_stats_plugin_query_propagates(fake_node):
    """The production inject path: a serving server configured with
    the plugin socket reports the plugin's device health in /stats,
    and the plugin-side spans join the serving process's traces."""
    import urllib.request

    from container_engine_accelerators_tpu.serving import (
        InferenceServer,
    )

    mgr = _make_manager(fake_node)
    with ServingManager(mgr, short_tmpdir()) as sm:
        srv = InferenceServer(
            "m", lambda v, x, t: (x.sum(axis=(1, 2))[:, None], {}),
            {"params": {}}, input_shape=(2, 2), port=0, max_batch=2,
            max_wait_ms=1, plugin_socket=sm.socket_path())
        srv.start()
        try:
            stats = json.load(urllib.request.urlopen(
                f"http://localhost:{srv.port}/stats", timeout=30))
            assert stats["plugin_devices"] == {"accel0": "Healthy",
                                               "accel1": "Healthy"}
        finally:
            srv.stop()
    spans = {s["name"]: s for s in obs.TRACER.snapshot()["spans"]}
    query = spans["serving.plugin_query"]
    opts = spans["rpc.v1beta1.DevicePlugin/GetDevicePluginOptions"]
    assert opts["trace_id"] == query["trace_id"]


# -- two real processes + merge ---------------------------------------

_PLUGIN_PROC = textwrap.dedent("""
    import json, os, sys, threading
    sys.path.insert(0, {repo!r})
    from container_engine_accelerators_tpu import obs
    obs.set_role("plugin")
    from container_engine_accelerators_tpu.chip import PyChipBackend
    from container_engine_accelerators_tpu.plugin.manager import (
        TpuManager,
    )
    mgr = TpuManager(dev_dir={dev!r}, state_dir={state!r},
                     backend=PyChipBackend())
    mgr.start()
    t = threading.Thread(
        target=mgr.serve, args=({plugin_dir!r}, "kubelet.sock", "tpu"),
        daemon=True)
    t.start()
    assert mgr.wait_until_serving(10)
    print("READY", flush=True)
    sys.stdin.readline()  # parent closes stdin -> shut down
    mgr.stop()
    t.join(timeout=10)
""")

_CLIENT_PROC = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import grpc
    from container_engine_accelerators_tpu import obs
    obs.set_role("serving")
    from container_engine_accelerators_tpu.obs.grpc_client import (
        traced_channel,
    )
    from container_engine_accelerators_tpu.plugin import api
    with grpc.insecure_channel("unix://" + {sock!r}) as raw:
        stub = api.DevicePluginV1Beta1Stub(traced_channel(raw))
        with obs.span("serving.request", origin="client-proc") as sp:
            stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                container_requests=[
                    api.v1beta1_pb2.ContainerAllocateRequest(
                        devicesIDs=["accel0"])]), timeout=10)
            print(obs.format_traceparent(sp.context()), flush=True)
""")


def test_cross_process_journals_merge(fake_node, tmp_path):
    """The full acceptance criterion, with two REAL processes: the
    client process's span context propagates into the plugin
    process's journal, and trace_dump --merge of the two journal
    files yields one Perfetto file with both processes on distinct
    named tracks."""
    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("1x2")
    plugin_dir = short_tmpdir()
    plugin_journal = tmp_path / "plugin_journal.json"
    client_journal = tmp_path / "client_journal.json"

    env_base = dict(os.environ, PYTHONPATH=REPO_ROOT)
    env_base.pop("CEA_TPU_TRACE_FILE", None)
    plugin = subprocess.Popen(
        [sys.executable, "-c", _PLUGIN_PROC.format(
            repo=REPO_ROOT, dev=fake_node.dev_dir,
            state=fake_node.state_dir, plugin_dir=plugin_dir)],
        env=dict(env_base, CEA_TPU_TRACE_FILE=str(plugin_journal)),
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        cwd=REPO_ROOT)
    try:
        assert plugin.stdout.readline().strip() == "READY"
        socks = [f for f in os.listdir(plugin_dir)
                 if f.startswith("tpu-") and f.endswith(".sock")]
        assert len(socks) == 1
        sock = os.path.join(plugin_dir, socks[0])

        client = subprocess.run(
            [sys.executable, "-c", _CLIENT_PROC.format(
                repo=REPO_ROOT, sock=sock)],
            env=dict(env_base, CEA_TPU_TRACE_FILE=str(client_journal)),
            capture_output=True, text=True, timeout=120,
            cwd=REPO_ROOT)
        assert client.returncode == 0, client.stderr[-2000:]
        caller_trace, caller_span = obs.parse_traceparent(
            client.stdout.strip().splitlines()[-1])
    finally:
        try:
            plugin.stdin.close()
            plugin.wait(timeout=15)
        except Exception:
            plugin.kill()
            raise
    assert plugin.returncode == 0

    # The plugin journal's Allocate span is parented under the
    # CALLER's trace/span ids — ids minted in a different process.
    plug = json.loads(plugin_journal.read_text())
    assert plug["identity"]["role"] == "plugin"
    rpc = [s for s in plug["spans"]
           if s["name"] == "rpc.v1beta1.DevicePlugin/Allocate"]
    assert rpc, [s["name"] for s in plug["spans"]]
    assert rpc[0]["trace_id"] == caller_trace
    cli = json.loads(client_journal.read_text())
    assert cli["identity"]["role"] == "serving"
    client_rpc_span = [
        s for s in cli["spans"]
        if s["name"] == "rpc.client.v1beta1.DevicePlugin/Allocate"]
    assert rpc[0]["parent_id"] == client_rpc_span[0]["span_id"]
    assert rpc[0]["parent_id"] != caller_span  # via the client span

    # trace_dump --merge: one Perfetto file, two named process tracks.
    spec = importlib.util.spec_from_file_location(
        "trace_dump", os.path.join(REPO_ROOT, "tools",
                                   "trace_dump.py"))
    trace_dump = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_dump)
    out = tmp_path / "merged.perfetto.json"
    rc = trace_dump.main(["--merge", str(client_journal),
                          str(plugin_journal), "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    by_name = {}
    for ev in doc["traceEvents"]:
        by_name.setdefault(ev["name"], []).append(ev)
    req = by_name["serving.request"][0]
    alloc = by_name["rpc.v1beta1.DevicePlugin/Allocate"][0]
    assert req["pid"] != alloc["pid"]  # distinct process tracks
    assert (req["args"]["trace_id"] == alloc["args"]["trace_id"]
            == caller_trace)
    labels = {ev["args"]["name"]
              for ev in by_name.get("process_name", [])}
    assert any(lbl.startswith("serving@") for lbl in labels), labels
    assert any(lbl.startswith("plugin@") for lbl in labels), labels

    # tools/goodput_report.py over the same two-process journals: a
    # goodput ratio and a per-bucket breakdown whose buckets sum to
    # the observed wall time within 1% — per process AND combined.
    spec = importlib.util.spec_from_file_location(
        "goodput_report", os.path.join(REPO_ROOT, "tools",
                                       "goodput_report.py"))
    goodput_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(goodput_report)
    report_out = tmp_path / "goodput.json"
    rc = goodput_report.main([str(client_journal),
                              str(plugin_journal),
                              "--out", str(report_out)])
    assert rc == 0
    report = json.loads(report_out.read_text())
    assert len(report["processes"]) == 2
    assert {p["identity"]["role"] for p in report["processes"]} \
        == {"serving", "plugin"}
    for scope in report["processes"] + [report["combined"]]:
        total = sum(scope["buckets"].values())
        assert total == pytest.approx(scope["wall_s"], rel=0.01,
                                      abs=1e-6)
    # No train spans in these journals: everything lands honestly in
    # "other", and the ratio reports 0 productive — never a fake
    # positive.
    assert report["combined"]["wall_s"] > 0
    assert report["combined"]["goodput_ratio"] == 0.0
