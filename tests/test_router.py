# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Fleet-router contracts: tenant deficit math, the placement order
(affinity -> bounded-load spill -> hedge -> least-loaded with live
in-flight counts), shed statuses with derived Retry-After, and the
mid-stream failover splice — against the injected fake fleet from
test_fleet plus scripted stdlib HTTP engines (tools/router_check.py
drives the real-engine version at scale; the slow test here is the
two-real-process kernel of it)."""

import http.client
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.obs.fleet import (
    FleetCollector,
)
from container_engine_accelerators_tpu.obs.trace import Tracer
from container_engine_accelerators_tpu.serving.router import (
    REASON_AFFINITY,
    REASON_HEDGE,
    REASON_LEAST_LOADED,
    REASON_SPILL,
    SHED_NO_ENGINES,
    SHED_SATURATED,
    SHED_TENANT_RATE,
    RouterCore,
    RouterServer,
    TenantLedger,
    parse_weights,
)
from tests.test_fleet import FakeFleet, make_collector

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BS = 4
KEYED = [1, 2, 3, 4, 5, 6, 7, 8]       # two full BS=4 blocks
UNKEYED = [1, 2, 3]                    # below one block: no key


def _sat(x):
    return {"max": x, "causes": {"slots": x}}


def make_core(fleet, **kw):
    coll = make_collector(fleet, Tracer(enabled=True))
    coll.poll_once()
    kw.setdefault("block_size", BS)
    kw.setdefault("shed_sat", 0.9)
    kw.setdefault("tenants", TenantLedger(rate=0))
    kw.setdefault("spill_bound", 2)
    return coll, RouterCore(coll, **kw)


# ---------------------------------------------------------------------------
# tenant fairness
# ---------------------------------------------------------------------------


def test_parse_weights_tolerates_junk():
    assert parse_weights("a=2, b=0.5") == {"a": 2.0, "b": 0.5}
    assert parse_weights("a=2,,=3,c=x,d=-1,e") == {"a": 2.0}
    assert parse_weights("") == {}
    assert parse_weights(None) == {}


def test_tenant_ledger_weighted_deficit_math():
    now = [1000.0]
    led = TenantLedger(rate=10.0, burst_s=2.0, weights={"big": 2.0},
                       clock=lambda: now[0])
    # New tenants start with a full burst: rate * weight * burst_s.
    ok, wait = led.admit("small", 20)
    assert ok and wait is None
    ok, wait = led.admit("small", 1)
    assert not ok and wait == 1
    ok, wait = led.admit("big", 40)
    assert ok and wait is None
    # Refill is continuous: 1s at weight-1 rate 10 -> 10 tokens.
    now[0] += 1.0
    ok, _ = led.admit("small", 10)
    assert ok
    # A cost above the burst cap quotes the FULL-cap wait (it can
    # never admit sooner), not the unreachable cost.
    ok, wait = led.admit("small", 1000)
    assert not ok and wait == 2
    # rate <= 0 disables fairness entirely.
    assert TenantLedger(rate=0).admit("anyone", 10 ** 9) == (True, None)


def test_tenant_shed_is_429_with_retry_after():
    fleet = FakeFleet()
    now = [0.0]
    _, core = make_core(fleet, tenants=TenantLedger(
        rate=10.0, burst_s=1.0, clock=lambda: now[0]))
    assert core.route(UNKEYED, 10)["action"] == "route"
    decision = core.route(UNKEYED, 10)
    assert decision == {"action": "shed", "status": 429,
                        "reason": SHED_TENANT_RATE, "retry_after": 1}
    assert core.stats()["shed"] == {SHED_TENANT_RATE: 1}


# ---------------------------------------------------------------------------
# placement order
# ---------------------------------------------------------------------------


def test_unkeyed_routes_least_loaded_and_inflight_spreads():
    fleet = FakeFleet()
    _, core = make_core(fleet)
    d = core.route(UNKEYED, 10)
    assert d["action"] == "route" and d["key"] is None
    assert d["reason"] == REASON_LEAST_LOADED
    assert d["url"] == fleet.urls[0]   # all-equal tie: URL order
    # The router's own in-flight counts break the next tie: an
    # untouched engine beats the one just aimed at.
    core.inflight_begin(fleet.urls[0])
    assert core.route(UNKEYED, 10)["url"] == fleet.urls[1]
    core.inflight_end(fleet.urls[0])
    assert core.route(UNKEYED, 10)["url"] == fleet.urls[0]


def test_inflight_outranks_stale_saturation():
    # An engine's published saturation PARKS at its last value when
    # it idles; a poll-stale 0.25 must not outrank live placement.
    fleet = FakeFleet()
    fleet.engines[fleet.urls[0]]["saturation"] = _sat(0.25)
    coll, core = make_core(fleet)
    coll.poll_once()
    assert core.route(UNKEYED, 10)["url"] == fleet.urls[1]
    core.inflight_begin(fleet.urls[1])
    core.inflight_begin(fleet.urls[2])
    assert core.route(UNKEYED, 10)["url"] == fleet.urls[0]


def test_affinity_seed_hit_and_lru_cap():
    fleet = FakeFleet()
    _, core = make_core(fleet, affinity_cap=2)
    seed = core.route(KEYED, 10)
    assert seed["reason"] == REASON_LEAST_LOADED
    assert seed["key"] is not None
    home = seed["url"]
    # Load the fleet elsewhere: the pin must override least-loaded.
    for url in fleet.urls:
        if url != home:
            continue
        core.inflight_begin(url)
    hit = core.route(KEYED, 10)
    assert hit == {"action": "route", "url": home,
                   "reason": REASON_AFFINITY, "key": seed["key"]}
    stats = core.stats()["affinity"]
    assert (stats["lookups"], stats["hits"]) == (2, 1)
    assert stats["hit_rate"] == 0.5
    # The map is LRU-bounded: a third distinct prefix evicts the
    # oldest of the two when the cap is 2.
    core.route([9] * 8, 10)
    core.route([11] * 8, 10)
    snap = core.affinity_snapshot()
    assert len(snap) == 2 and seed["key"].hex() not in snap


def test_hedge_repoints_when_home_is_hot():
    fleet = FakeFleet()
    coll, core = make_core(fleet)
    home = core.route(KEYED, 10)["url"]
    fleet.engines[home]["saturation"] = _sat(0.95)
    coll.poll_once()
    d = core.route(KEYED, 10)
    assert d["reason"] == REASON_HEDGE and d["url"] != home
    # The blocks will be rebuilt where the hedge landed: map follows.
    assert core.affinity_snapshot()[d["key"].hex()] == d["url"]


def test_spill_past_bound_without_repointing():
    fleet = FakeFleet()
    coll, core = make_core(fleet, spill_bound=2)
    seed = core.route(KEYED, 10)
    home, key = seed["url"], seed["key"]
    fleet.engines[home]["queue_depth"] = 5   # bound(2) + best(0) < 5
    coll.poll_once()
    d = core.route(KEYED, 10)
    assert d["reason"] == REASON_SPILL and d["url"] != home
    # Spill is an overflow, not a migration: the map stays put and
    # the request does NOT count as an affinity hit.
    assert core.affinity_snapshot()[key.hex()] == home
    assert core.stats()["affinity"]["hits"] == 0
    # Load drains -> the pin resumes.
    fleet.engines[home]["queue_depth"] = 1
    coll.poll_once()
    assert core.route(KEYED, 10)["reason"] == REASON_AFFINITY


def test_spill_bound_zero_disables():
    fleet = FakeFleet()
    coll, core = make_core(fleet, spill_bound=0)
    home = core.route(KEYED, 10)["url"]
    fleet.engines[home]["queue_depth"] = 50
    coll.poll_once()
    assert core.route(KEYED, 10) == {
        "action": "route", "url": home, "reason": REASON_AFFINITY,
        "key": core.route(KEYED, 10)["key"]}


# ---------------------------------------------------------------------------
# shedding and siblings
# ---------------------------------------------------------------------------


def test_saturated_fleet_sheds_with_ramp_retry_after():
    fleet = FakeFleet()
    for url in fleet.urls:
        fleet.engines[url]["saturation"] = _sat(0.95)
    coll, core = make_core(fleet)
    coll.poll_once()
    d = core.route(UNKEYED, 10)
    # No engine published a horizon: the single-engine overload ramp
    # 1 + 4 * sat quotes the wait (min over engines, rounded).
    assert d == {"action": "shed", "status": 503,
                 "reason": SHED_SATURATED, "retry_after": 5}


def test_dead_fleet_sheds_no_engines():
    fleet = FakeFleet()
    coll, core = make_core(fleet, shed_sat=2.0)
    for url in fleet.urls:
        fleet.engines[url]["alive"] = False
    for _ in range(3):   # past the down hysteresis
        fleet.now += 10.0
        coll.poll_once()
    d = core.route(UNKEYED, 10)
    assert d["action"] == "shed" and d["status"] == 503
    assert d["reason"] == SHED_NO_ENGINES and d["retry_after"] >= 1


def test_draining_horizon_caps_retry_after():
    fleet = FakeFleet()
    for url in fleet.urls:
        fleet.engines[url]["ready"] = False
        fleet.engines[url]["detail"] = {"state": "draining",
                                        "retry_after_s": 7.0,
                                        "saturation_cause": None}
    coll, core = make_core(fleet)
    coll.poll_once()
    d = core.route(UNKEYED, 10)
    assert d["reason"] == SHED_NO_ENGINES and d["retry_after"] == 7


def test_sibling_prefers_cold_falls_back_hot():
    fleet = FakeFleet()
    failed, hot, cold = fleet.urls
    fleet.engines[hot]["saturation"] = _sat(0.95)
    coll, core = make_core(fleet)
    coll.poll_once()
    assert core.sibling({failed}) == cold
    # With every survivor hot, a hot sibling still beats a dropped
    # stream.
    assert core.sibling({failed, cold}) == hot
    assert core.sibling(set(fleet.urls)) is None


# ---------------------------------------------------------------------------
# the stream splice against scripted HTTP engines
# ---------------------------------------------------------------------------


class ScriptedEngine:
    """A stdlib HTTP engine that answers the collector's poll
    surfaces and streams a scripted ndjson plan on POST. Plans:
    ("tokens", [..]) lines, "die" (drop the connection mid-stream),
    "done", or ("envelope", {...})."""

    def __init__(self):
        self.plan = []
        self.requests = []       # payloads this engine received
        self.headers = []        # header dicts, parallel to requests
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *args):
                pass

            def _json(self, body):
                payload = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length",
                                 str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                path = self.path.partition("?")[0]
                if path == "/stats":
                    self._json({
                        "engine_id": f"fake@{outer.port}",
                        "requests_retired": 0,
                        "queue_depth": 0,
                        "slo": {"violations": {}},
                        "saturation": {"max": 0.0, "causes": {}},
                    })
                elif path == "/metrics":
                    self.send_response(200)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                elif path in ("/readyz", "/healthz"):
                    self._json({"status": "ok"})
                elif path.startswith("/debug/requests"):
                    self._json({"retired_total": 0, "records": []})
                else:
                    self.send_error(404)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length))
                outer.requests.append(payload)
                outer.headers.append(
                    {k.lower(): v for k, v in self.headers.items()})
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.end_headers()
                for step in outer.plan:
                    if step == "die":
                        self.wfile.flush()
                        self.connection.close()
                        return
                    if step == "done":
                        self.wfile.write(b'{"done": true}\n')
                    elif step[0] == "tokens":
                        self.wfile.write(json.dumps(
                            {"tokens": step[1]}).encode() + b"\n")
                    else:
                        self.wfile.write(json.dumps(
                            step[1]).encode() + b"\n")
                    self.wfile.flush()

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _stream_through_router(port, payload, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/v1/models/lm:generate",
                 body=json.dumps(payload).encode(),
                 headers=dict({"Content-Type": "application/json"},
                              **(headers or {})))
    resp = conn.getresponse()
    lines = []
    while True:
        raw = resp.readline()
        if not raw:
            break
        if raw.strip():
            lines.append(json.loads(raw))
    conn.close()
    return resp.status, lines


@pytest.fixture
def scripted_pair():
    engines = [ScriptedEngine(), ScriptedEngine()]
    # The router breaks the all-idle tie lexicographically: the
    # URL-smallest engine receives the first request.
    first, second = sorted(engines, key=lambda e: e.url)
    collector = FleetCollector([e.url for e in engines],
                               poll_ms=10000.0)
    core = RouterCore(collector, block_size=BS, shed_sat=2.0,
                      tenants=TenantLedger(rate=0))
    server = RouterServer(core, collector, port=0, timeout_s=10.0)
    collector.poll_once()
    server.start()
    try:
        yield first, second, core, server
    finally:
        server.stop()
        for e in engines:
            e.stop()


def test_stream_splice_resubmits_prompt_plus_delivered(scripted_pair):
    first, second, core, server = scripted_pair
    first.plan = [("tokens", [10]), ("tokens", [11]), "die"]
    second.plan = [("tokens", [12]), ("tokens", [13]), "done"]
    status, lines = _stream_through_router(server.port, {
        "prompts": [UNKEYED], "max_new_tokens": 4, "stream": True})
    assert status == 200
    assert lines == [{"tokens": [10]}, {"tokens": [11]},
                     {"tokens": [12]}, {"tokens": [13]},
                     {"done": True}]
    # The cross-process replay contract: the sibling's prompt is
    # prompt + every delivered token, its budget what remains.
    (replay,) = second.requests
    assert replay["prompts"] == [UNKEYED + [10, 11]]
    assert replay["max_new_tokens"] == 2
    assert core.stats()["failover"] == 1


def test_stream_splice_closes_clean_when_budget_spent(scripted_pair):
    first, second, core, server = scripted_pair
    first.plan = [("tokens", [10]), ("tokens", [11]), "die"]
    status, lines = _stream_through_router(server.port, {
        "prompts": [UNKEYED], "max_new_tokens": 2, "stream": True})
    # Everything owed was delivered before the death: the splice is
    # a bare close, no sibling contacted.
    assert status == 200
    assert lines == [{"tokens": [10]}, {"tokens": [11]},
                     {"done": True}]
    assert second.requests == []


def test_fatal_envelope_is_relayed_not_retried(scripted_pair):
    first, second, core, server = scripted_pair
    first.plan = [("tokens", [10]),
                  ("envelope", {"error": "boom", "retryable": False})]
    status, lines = _stream_through_router(server.port, {
        "prompts": [UNKEYED], "max_new_tokens": 4, "stream": True})
    assert status == 200   # headers were already streaming
    assert lines[0] == {"tokens": [10]}
    assert lines[-1]["error"] == "boom"
    assert second.requests == []
    assert core.stats()["failover"] == 0


def test_failover_exhausted_surfaces_envelope(scripted_pair):
    first, second, core, server = scripted_pair
    first.plan = [("tokens", [10]), "die"]
    second.plan = [("tokens", [11]), "die"]
    status, lines = _stream_through_router(server.port, {
        "prompts": [UNKEYED], "max_new_tokens": 8, "stream": True})
    assert status == 200
    assert lines[:2] == [{"tokens": [10]}, {"tokens": [11]}]
    tail = lines[-1]
    assert "failover exhausted" in tail["error"] and tail["retryable"]
    # One hop spent (first -> second); a tried engine is never
    # retried, so the second death exhausts the stream.
    assert core.stats()["failover"] == 1
    assert core.stats()["shed"] == {"failover_exhausted": 1}


def test_unary_failover_retries_on_sibling(scripted_pair):
    first, second, core, server = scripted_pair
    # A dead-socket engine: stop it so the unary POST fails outright.
    first.stop()
    second.plan = [("tokens", [12]), "done"]
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=30)
    conn.request("POST", "/v1/models/lm:generate",
                 body=json.dumps({"prompts": [UNKEYED],
                                  "max_new_tokens": 2}).encode())
    resp = conn.getresponse()
    assert resp.status == 200
    conn.close()
    assert len(second.requests) == 1
    assert core.stats()["failover"] == 1


# ---------------------------------------------------------------------------
# request journeys: trace propagation + latency attribution
# ---------------------------------------------------------------------------


def _router_debug_requests(port):
    import urllib.request

    return json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/debug/requests", timeout=10))


def _event_count(name):
    return sum(1 for e in obs.TRACER.snapshot()["events"]
               if e.get("name") == name)


def test_splice_preserves_trace_and_request_id(scripted_pair):
    """One journey, one identity: the inbound carrier's trace id and
    request id ride BOTH hops of a mid-stream failover splice, the
    spliced stream stays token-identical, and the router's journey
    record attributes the whole wall to named buckets including the
    splice."""
    first, second, core, server = scripted_pair
    first.plan = [("tokens", [10]), ("tokens", [11]), "die"]
    second.plan = [("tokens", [12]), ("tokens", [13]), "done"]
    inbound_ctx = (0xfeedface12345678, 0xabcdef01)
    failovers_before = _event_count("router.engine_failover")
    status, lines = _stream_through_router(
        server.port,
        {"prompts": [UNKEYED], "max_new_tokens": 4, "stream": True},
        headers=obs.inject_headers(inbound_ctx,
                                   request_id="jrny-01"))
    assert status == 200
    assert lines == [{"tokens": [10]}, {"tokens": [11]},
                     {"tokens": [12]}, {"tokens": [13]},
                     {"done": True}]
    # Both hops carried ONE carrier: same trace id (the inbound
    # caller's), same request id — the sibling resubmit bills to the
    # original request, not a fresh identity.
    (h1,), (h2,) = first.headers, second.headers
    for h in (h1, h2):
        assert h["x-cea-request-id"] == "jrny-01"
        ctx = obs.parse_traceparent(h["traceparent"])
        assert ctx is not None and ctx[0] == inbound_ctx[0]
    # The journey record: adopted identity, a splice hop, and
    # buckets that partition the wall.
    (rec,) = _router_debug_requests(server.port)["records"]
    assert rec["request_id"] == "jrny-01"
    assert rec["trace_id"] == "%x" % inbound_ctx[0]
    assert rec["outcome"] == "completed"
    assert rec["engine"] == second.url     # where the stream ended
    assert rec["hops"] == 1
    assert rec["tokens"] == 4
    buckets = rec["buckets"]
    assert buckets["splice_resubmit"] > 0
    assert buckets["upstream_ttfb"] > 0
    assert sum(buckets.values()) == pytest.approx(
        rec["wall_s"], rel=0.01, abs=1e-4)
    # The dead engine opened exactly one failover episode.
    assert _event_count("router.engine_failover") \
        == failovers_before + 1


def test_shed_journey_retires_with_cause(scripted_pair):
    """A shed is still a journey: the 429 retires a ledger record
    with the shed outcome, zero hops, and the adopted request id."""
    first, second, core, server = scripted_pair
    core.tenants = TenantLedger(rate=0.001, burst_s=1.0)
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=30)
    conn.request("POST", "/v1/models/lm:generate",
                 body=json.dumps({"prompts": [UNKEYED],
                                  "max_new_tokens": 4,
                                  "tenant": "acme"}).encode(),
                 headers={"x-cea-request-id": "shed-01"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 429
    assert body["request_id"] == "shed-01"
    recs = _router_debug_requests(server.port)["records"]
    (rec,) = [r for r in recs if r["request_id"] == "shed-01"]
    assert rec["outcome"] == "shed_tenant_rate"
    assert rec["hops"] == 0 and rec["engine"] is None
    assert rec["tenant"] == "acme"
    payload = _router_debug_requests(server.port)
    assert payload["tenants"]["acme"]["requests"] == 1


def test_tenant_shed_episode_hysteresis():
    """Episode-wise journaling: a burst of tenant sheds emits ONE
    router.tenant_shed event; a quiet gap past episode_clear_s
    re-arms it; distinct tenants are independent episodes."""
    t = [0.0]
    _, core = make_core(
        FakeFleet(), tenants=TenantLedger(rate=1.0, burst_s=1.0),
        clock=lambda: t[0], episode_clear_s=5.0)
    before = _event_count("router.tenant_shed")
    for _ in range(3):      # rapid burst: one open episode
        d = core.route(UNKEYED, 100, tenant="acme")
        assert d["action"] == "shed" \
            and d["reason"] == SHED_TENANT_RATE
        t[0] += 1.0
    assert _event_count("router.tenant_shed") == before + 1
    t[0] += 10.0            # quiet gap closes the episode
    core.route(UNKEYED, 100, tenant="acme")
    assert _event_count("router.tenant_shed") == before + 2
    core.route(UNKEYED, 100, tenant="zeta")  # independent key
    assert _event_count("router.tenant_shed") == before + 3
    # The per-request shed counter saw every one of them.
    assert core.stats()["shed"] == {SHED_TENANT_RATE: 5}


# ---------------------------------------------------------------------------
# two real engines: the failover splice is token-identical
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_two_process_failover_stream_token_identical():
    """The kernel of tools/router_check.py leg 3: two real
    GenerationServer processes (ONE model seed), a mid-stream
    SIGKILL, and the spliced stream must equal the sibling's
    uninterrupted greedy decode."""
    tmpdir = tempfile.mkdtemp(prefix="router_test_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs, urls = [], []
    worker = os.path.join(REPO, "tools", "serve_fleet.py")
    for i in range(2):
        port_file = os.path.join(tmpdir, f"e{i}.port")
        procs.append((subprocess.Popen(
            [sys.executable, worker, "--worker",
             "--port-file", port_file, "--seed", "0"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL), port_file))
    collector = core = server = None
    try:
        deadline = time.monotonic() + 300
        for proc, port_file in procs:
            while not os.path.exists(port_file):
                assert proc.poll() is None, "engine died warming up"
                assert time.monotonic() < deadline, "warm-up timeout"
                time.sleep(0.2)
            with open(port_file) as f:
                urls.append(f"http://127.0.0.1:{f.read().strip()}")
        collector = FleetCollector(urls, poll_ms=250.0)
        core = RouterCore(collector, shed_sat=2.0,
                          tenants=TenantLedger(rate=0))
        server = RouterServer(core, collector, port=0)
        collector.start()
        server.start()

        prompt, max_new = [1, 2, 3, 4, 5], 20
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=120)
        conn.request("POST", "/v1/models/lm:generate",
                     body=json.dumps({"prompts": [prompt],
                                      "max_new_tokens": max_new,
                                      "stream": True}).encode())
        # The in-flight ledger names the engine holding the stream
        # the moment the router aims at it — kill the victim BEFORE
        # it can finish the tiny decode, so the splice really runs.
        kill_deadline = time.monotonic() + 60
        while not core._inflight:
            assert time.monotonic() < kill_deadline
            time.sleep(0.001)
        (victim,) = list(core._inflight)
        sibling = next(u for u in urls if u != victim)
        victim_proc = next(
            p for (p, pf), u in zip(procs, urls) if u == victim)
        victim_proc.kill()
        resp = conn.getresponse()
        assert resp.status == 200
        tokens = []
        # The sibling's uninterrupted greedy decode is the oracle
        # (same seed -> same weights -> token-identical).
        ref_conn = http.client.HTTPConnection(
            sibling.split("//")[1].split(":")[0],
            int(sibling.rsplit(":", 1)[1]), timeout=120)
        ref_conn.request("POST", "/v1/models/lm:generate",
                         body=json.dumps(
                             {"prompts": [prompt],
                              "max_new_tokens": max_new}).encode())
        ref = json.loads(ref_conn.getresponse().read())
        ref_conn.close()
        reference = ref["sequences"][0][len(prompt):]
        while True:
            raw = resp.readline()
            assert raw, "stream truncated without done"
            line = json.loads(raw)
            if line.get("done"):
                break
            assert "error" not in line, line
            tokens.extend(line["tokens"])
        conn.close()
        assert tokens == reference
        assert core.stats()["failover"] >= 1
    finally:
        if server is not None:
            server.stop()
        if collector is not None:
            collector.stop()
        for proc, _ in procs:
            if proc.poll() is None:
                proc.kill()
        for proc, _ in procs:
            proc.wait(timeout=15)
