# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""tpu_state_sampler + tpu_metrics_bridge: the telemetry producers.

Round-1 verdict item 3: the state-dir ABI had no producer on a real
node. These tests drive the C++ sampler binary against synthetic
sysfs trees / metric feeds (the same fake-hardware technique the
reference uses for /dev and /proc — SURVEY.md section 4) and check
the full loop: producer writes -> native chip backend reads ->
health/duty/hbm surface correct values.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tests.conftest import NATIVE_LIB, REPO_ROOT

SAMPLER = os.path.join(REPO_ROOT, "build", "tpu_state_sampler")
BRIDGE = os.path.join(REPO_ROOT, "cmd", "tpu_metrics_bridge.py")


def _ensure_sampler():
    if not os.path.exists(SAMPLER):
        subprocess.run(
            ["make", "-C", os.path.join(REPO_ROOT, "native", "sampler")],
            check=False, capture_output=True)
    return os.path.exists(SAMPLER)


pytestmark = pytest.mark.skipif(
    not _ensure_sampler(), reason="sampler binary failed to build")


def _mknode(tmp_path, chips=2):
    dev = tmp_path / "dev"
    state = tmp_path / "state"
    sysfs = tmp_path / "sysfs"
    dev.mkdir()
    state.mkdir()
    sysfs.mkdir()
    for i in range(chips):
        (dev / f"accel{i}").touch()
    return dev, state, sysfs


def _run_once(dev, state, sysfs, *extra):
    subprocess.run(
        [SAMPLER, "--dev-dir", str(dev), "--state-dir", str(state),
         "--sysfs-root", str(sysfs), "--once", *extra],
        check=True, capture_output=True, timeout=30)


def test_health_probe_marks_present_chips_ok(tmp_path):
    dev, state, sysfs = _mknode(tmp_path)
    _run_once(dev, state, sysfs)
    for i in range(2):
        health = (state / f"accel{i}" / "health").read_text().strip()
        assert health == "ok"


def test_sysfs_error_counter_marks_chip_wedged(tmp_path):
    dev, state, sysfs = _mknode(tmp_path)
    d = sysfs / "accel1" / "device"
    d.mkdir(parents=True)
    (d / "errors").write_text("3\n")
    _run_once(dev, state, sysfs)
    assert (state / "accel0" / "health").read_text().strip() == "ok"
    assert (state / "accel1" / "health").read_text().strip() == "wedged"


def test_sysfs_counters_published_verbatim(tmp_path):
    dev, state, sysfs = _mknode(tmp_path, chips=1)
    d = sysfs / "accel0" / "device"
    d.mkdir(parents=True)
    (d / "tc_busy_time_us").write_text("500000\n")
    (d / "tc_total_time_us").write_text("1000000\n")
    (d / "hbm_total_bytes").write_text(str(16 * 1024 ** 3))
    (d / "hbm_used_bytes").write_text(str(1024 ** 3))
    _run_once(dev, state, sysfs)
    busy, total = map(
        int, (state / "accel0" / "duty_cycle").read_text().split())
    assert (busy, total) == (500000, 1000000)
    hbm_total, hbm_used = map(
        int, (state / "accel0" / "hbm").read_text().split())
    assert (hbm_total, hbm_used) == (16 * 1024 ** 3, 1024 ** 3)


def test_feed_duty_integrates_to_cumulative_counters(tmp_path):
    """A steady 50% feed must integrate into counters whose ratio the
    native backend reads back as ~50%."""
    dev, state, sysfs = _mknode(tmp_path, chips=1)
    feed = tmp_path / "feed.jsonl"
    feed.write_text(json.dumps(
        {"ts_us": int(time.time() * 1e6),
         "chips": [{"chip": 0, "duty_pct": 50.0,
                    "hbm_total": 1000, "hbm_used": 10}]}) + "\n")
    proc = subprocess.Popen(
        [SAMPLER, "--dev-dir", str(dev), "--state-dir", str(state),
         "--sysfs-root", str(sysfs), "--feed-file", str(feed),
         "--interval-ms", "50"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.monotonic() + 20
        duty_path = state / "accel0" / "duty_cycle"
        while time.monotonic() < deadline:
            # Refresh mtime so the feed never goes stale mid-test.
            os.utime(feed)
            if duty_path.exists():
                busy, total = map(int, duty_path.read_text().split())
                if total > 200000:  # >= ~4 integration ticks
                    break
            time.sleep(0.05)
        else:
            pytest.fail("duty_cycle never accumulated")
    finally:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=10)
    assert busy == pytest.approx(total * 0.5, rel=0.15)
    hbm_total, hbm_used = map(
        int, (state / "accel0" / "hbm").read_text().split())
    assert (hbm_total, hbm_used) == (1000, 10)


def test_large_feed_last_line_wins(tmp_path):
    """The bridge trims the feed at ~200 lines (tens of KB); the
    sampler must read the true last line, not a truncated prefix."""
    dev, state, sysfs = _mknode(tmp_path, chips=1)
    feed = tmp_path / "feed.jsonl"
    lines = [json.dumps({"ts_us": i, "chips": [
        {"chip": 0, "health": "wedged",
         "hbm_total": 1, "hbm_used": 1}]}) for i in range(199)]
    lines.append(json.dumps({"ts_us": 199, "chips": [
        {"chip": 0, "health": "ok",
         "hbm_total": 4000, "hbm_used": 40}]}))
    feed.write_text("\n".join(lines) + "\n")
    assert feed.stat().st_size > 8192
    _run_once(dev, state, sysfs, "--feed-file", str(feed))
    assert (state / "accel0" / "health").read_text().strip() == "ok"
    hbm_total, hbm_used = map(
        int, (state / "accel0" / "hbm").read_text().split())
    assert (hbm_total, hbm_used) == (4000, 40)


def test_feed_health_overrides_probe(tmp_path):
    dev, state, sysfs = _mknode(tmp_path, chips=2)
    feed = tmp_path / "feed.jsonl"
    feed.write_text(json.dumps(
        {"ts_us": 1, "chips": [
            {"chip": 0, "health": "uncorrectable_ecc"},
            {"chip": 1, "health": "ok"}]}) + "\n")
    _run_once(dev, state, sysfs, "--feed-file", str(feed))
    assert ((state / "accel0" / "health").read_text().strip()
            == "uncorrectable_ecc")
    assert (state / "accel1" / "health").read_text().strip() == "ok"


def test_stale_feed_ignored(tmp_path):
    dev, state, sysfs = _mknode(tmp_path, chips=1)
    feed = tmp_path / "feed.jsonl"
    feed.write_text(json.dumps(
        {"ts_us": 1, "chips": [{"chip": 0, "health": "wedged"}]}) + "\n")
    old = time.time() - 3600
    os.utime(feed, (old, old))
    _run_once(dev, state, sysfs, "--feed-file", str(feed))
    # Stale feed -> fall back to the probe (regular file opens fine).
    assert (state / "accel0" / "health").read_text().strip() == "ok"


def test_counters_monotonic_across_restarts(tmp_path):
    dev, state, sysfs = _mknode(tmp_path, chips=1)
    d = sysfs / "accel0" / "device"
    d.mkdir(parents=True)
    (d / "tc_busy_time_us").write_text("100\n")
    (d / "tc_total_time_us").write_text("200\n")
    _run_once(dev, state, sysfs)
    (d / "tc_busy_time_us").write_text("300\n")
    (d / "tc_total_time_us").write_text("600\n")
    _run_once(dev, state, sysfs)
    busy, total = map(
        int, (state / "accel0" / "duty_cycle").read_text().split())
    assert (busy, total) == (300, 600)


def test_native_backend_reads_sampler_output(tmp_path):
    """Producer -> consumer loop: the backend that health/metrics use
    must read what the sampler wrote."""
    if NATIVE_LIB is None:
        pytest.skip("native lib unavailable")
    dev, state, sysfs = _mknode(tmp_path, chips=2)
    d = sysfs / "accel0" / "device"
    d.mkdir(parents=True)
    (d / "hbm_total_bytes").write_text(str(32 * 1024 ** 3))
    (d / "hbm_used_bytes").write_text(str(2 * 1024 ** 3))
    derr = sysfs / "accel1" / "device"
    derr.mkdir(parents=True)
    (derr / "errors").write_text("1\n")
    (state / "topology").write_text("1x2")
    _run_once(dev, state, sysfs)

    from container_engine_accelerators_tpu.chip import get_backend
    from container_engine_accelerators_tpu.chip.backend import Health
    b = get_backend()
    b.init(str(dev), str(state))
    assert b.chip_health(0) == Health.OK
    assert b.chip_health(1) == Health.WEDGED
    assert b.chip_hbm(0) == (32 * 1024 ** 3, 2 * 1024 ** 3)


def test_bridge_fake_source_feeds_sampler(tmp_path):
    """Full producer chain: bridge (fake telemetry) -> feed file ->
    sampler -> state dir."""
    dev, state, sysfs = _mknode(tmp_path, chips=2)
    feed = tmp_path / "feed.jsonl"
    subprocess.run(
        [sys.executable, BRIDGE, "--feed-file", str(feed),
         "--fake-chips", "2", "--once"],
        check=True, capture_output=True, timeout=60)
    line = json.loads(feed.read_text().splitlines()[-1])
    assert [c["chip"] for c in line["chips"]] == [0, 1]
    _run_once(dev, state, sysfs, "--feed-file", str(feed))
    hbm_total, hbm_used = map(
        int, (state / "accel0" / "hbm").read_text().split())
    assert hbm_total == 16 * 1024 ** 3
    assert hbm_used == 256 * 1024 ** 2


# Raw protobuf wire encoders for synthesizing drifted/alien proto
# revisions (shared by the codec tests below).
def _wire_varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _wire_ld(field, payload):
    return bytes([(field << 3) | 2]) + _wire_varint(len(payload)) + payload


def _wire_vint(field, v):
    return bytes([(field << 3) | 0]) + _wire_varint(v)


def _wire_dbl(field, v):
    import struct as s
    return bytes([(field << 3) | 1]) + s.pack("<d", v)


class _RuntimeMetrics:
    """In-repo runtime metric service speaking the vendored proto —
    the integration seam for the bridge's gRPC source (VERDICT r2 #3:
    decode by field number against a real server, walker only for
    unknown revisions)."""

    def __init__(self, gauges):
        # gauges: {metric_name: {device: value}}
        self.gauges = gauges
        self.requests = []

    def GetRuntimeMetric(self, request, context):
        from container_engine_accelerators_tpu.plugin import api

        self.requests.append(request.metric_name)
        resp = api.runtime_metrics_pb2.MetricResponse()
        resp.metric.name = request.metric_name
        for device, value in sorted(
                self.gauges.get(request.metric_name, {}).items()):
            m = resp.metric.metrics.add()
            m.attribute.key = "device-id"
            m.attribute.value.int_attr = device
            if isinstance(value, float):
                m.gauge.as_double = value
            else:
                m.gauge.as_int = value
        return resp


def _serve_runtime_metrics(servicer):
    from concurrent import futures

    import grpc

    from container_engine_accelerators_tpu.plugin import api

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    api.add_runtime_metric_service(servicer, server)
    port = server.add_insecure_port("localhost:0")
    server.start()
    return server, f"localhost:{port}"


def test_bridge_grpc_source_against_real_proto_server():
    """GrpcSource end-to-end over a real gRPC hop: typed decode must
    recover exact device ids and values (including device ids that the
    old heuristic would have confused with small gauge values)."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "cmd"))
    import tpu_metrics_bridge as bridge

    servicer = _RuntimeMetrics({
        # Integer duty gauge 2 on device 5 is the VERDICT r2 weak #2
        # swap case: the walker heuristic decodes it as {2: 5.0}
        # (value and device exchanged); the typed path cannot.
        bridge.GRPC_DUTY_METRIC: {0: 37.5, 5: 2},
        bridge.GRPC_HBM_USAGE_METRIC: {0: 123 * 2**20, 5: 456 * 2**20},
        bridge.GRPC_HBM_TOTAL_METRIC: {0: 16 * 2**30, 5: 16 * 2**30},
    })
    server, addr = _serve_runtime_metrics(servicer)
    try:
        chips = bridge.GrpcSource(addr).poll()
    finally:
        server.stop(grace=0)
    assert chips == [
        {"chip": 0, "duty_pct": 37.5, "hbm_used": 123 * 2**20,
         "hbm_total": 16 * 2**30},
        {"chip": 5, "duty_pct": 2.0, "hbm_used": 456 * 2**20,
         "hbm_total": 16 * 2**30},
    ]
    assert servicer.requests[0] == bridge.GRPC_DUTY_METRIC


def test_bridge_typed_decode_none_on_unknown_revision():
    """Bytes from a drifted proto revision must fall through to the
    walker (typed decoder returns None, not a wrong answer)."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "cmd"))
    import tpu_metrics_bridge as bridge

    # A revision where the gauge lives at field 3 (not 2) and the
    # device id is a bare varint inside the attribute (not AttrValue):
    # the synthetic shape from test_bridge_wire_codec_roundtrip.
    metrics = b"".join(
        _wire_ld(2, _wire_ld(1, _wire_vint(2, dev))
                 + _wire_ld(3, _wire_dbl(1, 25.0 * (dev + 1))))
        for dev in range(2))
    drifted = _wire_ld(1, _wire_ld(1, b"name") + metrics)
    assert bridge.decode_gauges_typed(drifted) is None
    assert bridge.decode_gauges(drifted) == {0: 25.0, 1: 50.0}

    # And the vendored shape decodes typed, not via the walker.
    from container_engine_accelerators_tpu.plugin import api
    resp = api.runtime_metrics_pb2.MetricResponse()
    m = resp.metric.metrics.add()
    m.attribute.value.int_attr = 3
    m.gauge.as_int = 77
    assert bridge.decode_gauges_typed(
        resp.SerializeToString()) == {3: 77.0}


def test_bridge_wire_codec_roundtrip():
    """The tolerant decoder must extract per-device gauges from a
    response shaped like the runtime metric service's."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "cmd"))
    from tpu_metrics_bridge import (
        decode_gauges,
        encode_metric_request,
        parse_wire,
    )

    req = encode_metric_request("tpu.runtime.tensorcore.dutycycle.percent")
    fields = parse_wire(req)
    assert fields[0][0] == 1
    assert fields[0][2].decode().endswith("percent")

    # MetricResponse{ metric { metrics[] { attr{device=N} gauge{double} } } }
    metrics = b"".join(
        _wire_ld(2, _wire_ld(1, _wire_vint(2, dev))
                 + _wire_ld(3, _wire_dbl(1, 25.0 * (dev + 1))))
        for dev in range(2))
    resp = _wire_ld(1, _wire_ld(1, b"name") + metrics)
    gauges = decode_gauges(resp)
    assert gauges == {0: 25.0, 1: 50.0}
