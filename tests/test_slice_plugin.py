# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Subslice (MIG-analog) plugin behavior.

Mirrors mig/mig_test.go's partition discovery/DeviceSpec assertions,
recast for topology subslices: partitioned managers advertise slice
devices, Allocate hands out all member chips plus subslice-shaped
topology env.
"""

import os

import pytest

from container_engine_accelerators_tpu.chip import (
    NonUniformPartitionError,
    PyChipBackend,
)
from container_engine_accelerators_tpu.plugin import api
from container_engine_accelerators_tpu.plugin import manager as manager_mod
from container_engine_accelerators_tpu.plugin.config import TpuConfig
from container_engine_accelerators_tpu.plugin.manager import TpuManager
from container_engine_accelerators_tpu.plugin.slice import (
    SliceManager,
    is_slice_device_id,
    slice_device_id,
)
from tests.plugin_helpers import ServingManager, short_tmpdir


@pytest.fixture
def fast_intervals(monkeypatch):
    monkeypatch.setattr(manager_mod, "SOCKET_CHECK_INTERVAL_S", 0.1)
    monkeypatch.setattr(manager_mod, "CHIP_CHECK_INTERVAL_S", 5.0)


@pytest.fixture
def node8(fake_node):
    for i in range(8):
        fake_node.add_chip(i)
    fake_node.set_topology("2x4")
    return fake_node


def make_partitioned_manager(node, size="2x2"):
    m = TpuManager(dev_dir=node.dev_dir, state_dir=node.state_dir,
                   tpu_config=TpuConfig(tpu_partition_size=size),
                   backend=PyChipBackend())
    m.start()
    return m


def test_slice_manager_discovery(node8):
    backend = PyChipBackend()
    backend.init(node8.dev_dir, node8.state_dir)
    sm = SliceManager(backend)
    assert sm.start("2x2") == 2
    assert sorted(sm.list_devices()) == ["tpu-2x2-0", "tpu-2x2-1"]
    assert sm.slice_chips("tpu-2x2-0") == [0, 1, 4, 5]
    assert sm.slice_chips("tpu-2x2-1") == [2, 3, 6, 7]
    assert sm.owning_slice(6) == "tpu-2x2-1"
    assert sm.slice_chips("tpu-2x2-9") is None


def test_nonuniform_partition_rejected(node8):
    backend = PyChipBackend()
    backend.init(node8.dev_dir, node8.state_dir)
    sm = SliceManager(backend)
    with pytest.raises(NonUniformPartitionError):
        sm.start("2x3")


def test_partitioned_manager_advertises_slices(node8, fast_intervals):
    mgr = make_partitioned_manager(node8)
    devices = mgr.list_devices()
    assert sorted(devices) == ["tpu-2x2-0", "tpu-2x2-1"]
    assert all(h == api.HEALTHY for h in devices.values())


def test_partitioned_allocate_returns_all_chip_nodes(node8, fast_intervals):
    plugin_dir = short_tmpdir()
    with ServingManager(make_partitioned_manager(node8), plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            resp = stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                container_requests=[
                    api.v1beta1_pb2.ContainerAllocateRequest(
                        devicesIDs=["tpu-2x2-1"])]))
            cresp = resp.container_responses[0]
            assert [d.host_path for d in cresp.devices] == [
                os.path.join(node8.dev_dir, f"accel{i}")
                for i in (2, 3, 6, 7)]
            assert cresp.envs["TPU_VISIBLE_DEVICES"] == "2,3,6,7"
            # A 2x2 tile is a contiguous box on the torus.
            assert cresp.envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"


def test_slice_health_routing(node8, fast_intervals):
    mgr = make_partitioned_manager(node8)
    mgr.set_device_health("tpu-2x2-0", api.UNHEALTHY)
    assert mgr.list_devices()["tpu-2x2-0"] == api.UNHEALTHY
    with pytest.raises(ValueError):
        mgr.device_specs("tpu-2x2-0")
    # Mirror of manager.go:178-188: the slice manager saw the update.
    assert mgr._slice_mgr.list_devices()["tpu-2x2-0"] == api.UNHEALTHY


def test_failed_repartition_poisons_all_slices(node8, fast_intervals):
    """Hot-unplug that breaks the tiling must never serve stale chip
    sets: every slice goes Unhealthy under the old ids (VERDICT r2 #5;
    invariant source mig.go:190-201)."""
    mgr = make_partitioned_manager(node8)
    assert all(h == api.HEALTHY for h in mgr.list_devices().values())
    node8.remove_chip(7)
    assert mgr.has_new_devices()
    mgr._refresh_devices()  # what the serve loop does on True
    devices = mgr.list_devices()
    # Ids stay stable (kubelet sees known devices go unhealthy, not
    # vanish) but everything is refused.
    assert sorted(devices) == ["tpu-2x2-0", "tpu-2x2-1"]
    assert all(h == api.UNHEALTHY for h in devices.values())
    assert mgr._slice_mgr.poisoned is not None
    for dev_id in devices:
        with pytest.raises(ValueError):
            mgr.device_specs(dev_id)


def test_repartition_recovers_when_topology_tiles_again(
        node8, fast_intervals):
    mgr = make_partitioned_manager(node8)
    node8.remove_chip(7)
    assert mgr.has_new_devices()
    mgr._refresh_devices()
    assert all(h == api.UNHEALTHY for h in mgr.list_devices().values())
    node8.add_chip(7)
    assert mgr.has_new_devices()
    mgr._refresh_devices()
    devices = mgr.list_devices()
    assert sorted(devices) == ["tpu-2x2-0", "tpu-2x2-1"]
    assert all(h == api.HEALTHY for h in devices.values())
    assert mgr._slice_mgr.poisoned is None
    assert len(mgr.device_specs("tpu-2x2-1")) == 4


def test_poison_transition_reserves_without_id_change(
        node8, fast_intervals):
    """has_new_devices() must report True on pure health transitions
    (poison/recovery) even though the id set is unchanged, so the
    serve loop re-advertises."""
    mgr = make_partitioned_manager(node8)
    assert not mgr.has_new_devices()  # steady state: no change
    node8.remove_chip(7)
    assert mgr.has_new_devices()      # poison transition
    mgr._refresh_devices()
    assert not mgr.has_new_devices()  # poisoned steady state
    node8.add_chip(7)
    assert mgr.has_new_devices()      # recovery transition
    mgr._refresh_devices()
    assert not mgr.has_new_devices()


def test_health_checker_cannot_unpoison(node8, fast_intervals):
    """The health checker's recovery branch calls
    set_device_health(dev, HEALTHY) when a slice's (stale) chips all
    look fine; while poisoned that must be refused — only a clean
    re-tiling restores schedulability."""
    mgr = make_partitioned_manager(node8)
    node8.remove_chip(7)
    assert mgr.has_new_devices()
    mgr._refresh_devices()
    # Slice 0's chips (0,1,4,5) are all still present and healthy; a
    # poll would try to "recover" it exactly like this:
    mgr.set_device_health("tpu-2x2-0", api.HEALTHY)
    assert mgr.list_devices()["tpu-2x2-0"] == api.UNHEALTHY
    assert mgr._slice_mgr.list_devices()["tpu-2x2-0"] == api.UNHEALTHY
    # Unhealthy transitions are still accepted while poisoned.
    mgr.set_device_health("tpu-2x2-1", api.UNHEALTHY)
    assert mgr.list_devices()["tpu-2x2-1"] == api.UNHEALTHY


def test_poisoned_retiling_retries_without_population_change(fake_node):
    """A poison can clear without another chip-set change (e.g. the
    node topology file settles); the rescan loop must keep retrying
    start() while poisoned."""
    for i in range(8):
        fake_node.add_chip(i)
    fake_node.set_topology("2x4")
    mgr = make_partitioned_manager(fake_node, size="2")
    assert sorted(mgr.list_devices()) == [f"tpu-2-{i}" for i in range(4)]
    # Drop to 6 chips: 2x4 topology now has holes -> poison.
    fake_node.remove_chip(6)
    fake_node.remove_chip(7)
    assert mgr.has_new_devices()
    mgr._refresh_devices()
    assert all(h == api.UNHEALTHY for h in mgr.list_devices().values())
    # Topology settles to 2x3 with the SAME chip population; the next
    # rescan must re-attempt the tiling and recover.
    fake_node.set_topology("2x3")
    assert mgr.has_new_devices()
    mgr._refresh_devices()
    devices = mgr.list_devices()
    assert sorted(devices) == [f"tpu-2-{i}" for i in range(3)]
    assert all(h == api.HEALTHY for h in devices.values())
    assert mgr._slice_mgr.poisoned is None


def test_slice_id_helpers():
    assert slice_device_id("2x2", 1) == "tpu-2x2-1"
    assert is_slice_device_id("tpu-2x2-1")
    assert not is_slice_device_id("accel0")


def test_slice_id_one_authority():
    """The id grammar must accept every shape parse_shape accepts
    (1-3 dims) and reject everything outside the namespace."""
    from container_engine_accelerators_tpu.plugin.slice import (
        parse_slice_device_id,
    )
    # 1-dim partition shapes are valid configs (parse_shape("4") ok).
    assert slice_device_id("4", 0) == "tpu-4-0"
    assert is_slice_device_id("tpu-4-0")
    assert parse_slice_device_id("tpu-4-0") == ("4", 0)
    assert parse_slice_device_id("tpu-2x2x2-3") == ("2x2x2", 3)
    for bad in ("tpu-2x2", "tpu--0", "tpu-2x-1", "tpu-2x2-", "tpu-2x2-a",
                "tpu-2x2x2x2-0", "xtpu-2x2-0"):
        assert not is_slice_device_id(bad), bad
