# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Both API versions served on one socket (multiple_versions_test.go)."""

import pytest

from container_engine_accelerators_tpu.chip import PyChipBackend
from container_engine_accelerators_tpu.plugin import api
from container_engine_accelerators_tpu.plugin import manager as manager_mod
from container_engine_accelerators_tpu.plugin.manager import TpuManager
from tests.plugin_helpers import ServingManager, short_tmpdir


@pytest.fixture
def fast_intervals(monkeypatch):
    monkeypatch.setattr(manager_mod, "SOCKET_CHECK_INTERVAL_S", 0.1)
    monkeypatch.setattr(manager_mod, "CHIP_CHECK_INTERVAL_S", 5.0)


def test_same_socket_serves_both_versions(fake_node, fast_intervals):
    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("1x2")
    mgr = TpuManager(dev_dir=fake_node.dev_dir, state_dir=fake_node.state_dir,
                     backend=PyChipBackend())
    mgr.start()
    plugin_dir = short_tmpdir()
    with ServingManager(mgr, plugin_dir) as sm:
        with sm.channel() as ch:
            beta = api.DevicePluginV1Beta1Stub(ch)
            alpha = api.DevicePluginV1AlphaStub(ch)

            beta_list = next(iter(beta.ListAndWatch(api.v1beta1_pb2.Empty())))
            alpha_list = next(iter(
                alpha.ListAndWatch(api.v1alpha_pb2.Empty())))
            assert ([d.ID for d in beta_list.devices]
                    == [d.ID for d in alpha_list.devices]
                    == ["accel0", "accel1"])

            beta_resp = beta.Allocate(api.v1beta1_pb2.AllocateRequest(
                container_requests=[
                    api.v1beta1_pb2.ContainerAllocateRequest(
                        devicesIDs=["accel0"])]))
            alpha_resp = alpha.Allocate(
                api.v1alpha_pb2.AllocateRequest(devicesIDs=["accel0"]))
            assert (beta_resp.container_responses[0].devices[0].host_path
                    == alpha_resp.devices[0].host_path)
