# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""v1beta1 plugin service tests over real gRPC loopback.

Mirrors beta_plugin_test.go: serve against a fake /dev, dial the
plugin socket as a DevicePluginClient, drive ListAndWatch and
Allocate, check hot-plug and negative paths.
"""

import os
import time

import grpc
import pytest

from container_engine_accelerators_tpu.chip import PyChipBackend
from container_engine_accelerators_tpu.plugin import api
from container_engine_accelerators_tpu.plugin import manager as manager_mod
from container_engine_accelerators_tpu.plugin.manager import TpuManager
from tests.plugin_helpers import KubeletStub, ServingManager, short_tmpdir


@pytest.fixture
def fast_intervals(monkeypatch):
    monkeypatch.setattr(manager_mod, "SOCKET_CHECK_INTERVAL_S", 0.1)
    monkeypatch.setattr(manager_mod, "CHIP_CHECK_INTERVAL_S", 0.5)


@pytest.fixture
def node4(fake_node):
    for i in range(4):
        fake_node.add_chip(i)
    fake_node.set_topology("2x2")
    return fake_node


def make_manager(node, **kwargs):
    m = TpuManager(dev_dir=node.dev_dir, state_dir=node.state_dir,
                   backend=PyChipBackend(), **kwargs)
    m.start()
    return m


def test_register_with_kubelet(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    stub = KubeletStub(os.path.join(plugin_dir, "kubelet.sock"))
    stub.start()
    try:
        with ServingManager(make_manager(node4), plugin_dir):
            assert stub.event.wait(5)
            req = stub.requests[0]
            assert req.version == api.V1BETA1_VERSION
            assert req.resource_name == "google.com/tpu"
            assert req.endpoint.startswith("tpu-")
            assert req.options.get_preferred_allocation_available
    finally:
        stub.stop()


def test_list_and_watch_and_allocate(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    with ServingManager(make_manager(node4), plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            stream = stub.ListAndWatch(api.v1beta1_pb2.Empty())
            first = next(iter(stream))
            assert [d.ID for d in first.devices] == [
                "accel0", "accel1", "accel2", "accel3"]
            assert all(d.health == api.HEALTHY for d in first.devices)

            resp = stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                container_requests=[
                    api.v1beta1_pb2.ContainerAllocateRequest(
                        devicesIDs=["accel0", "accel1"])]))
            assert len(resp.container_responses) == 1
            cresp = resp.container_responses[0]
            paths = [d.host_path for d in cresp.devices]
            assert paths == [os.path.join(node4.dev_dir, "accel0"),
                             os.path.join(node4.dev_dir, "accel1")]
            assert all(d.permissions == "mrw" for d in cresp.devices)
            assert cresp.envs["TPU_VISIBLE_DEVICES"] == "0,1"
            assert cresp.envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"
            assert cresp.envs["TPU_PROCESS_BOUNDS"] == "1,1,1"
            assert cresp.envs["TPU_WORKER_ID"] == "0"


def test_allocate_multi_container(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    with ServingManager(make_manager(node4), plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            resp = stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                container_requests=[
                    api.v1beta1_pb2.ContainerAllocateRequest(
                        devicesIDs=["accel0", "accel2"]),
                    api.v1beta1_pb2.ContainerAllocateRequest(
                        devicesIDs=["accel1"]),
                ]))
            assert len(resp.container_responses) == 2
            assert resp.container_responses[0].envs[
                "TPU_VISIBLE_DEVICES"] == "0,2"
            assert resp.container_responses[1].envs[
                "TPU_VISIBLE_DEVICES"] == "1"


def test_allocate_unknown_device_fails(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    with ServingManager(make_manager(node4), plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            with pytest.raises(grpc.RpcError) as err:
                stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                    container_requests=[
                        api.v1beta1_pb2.ContainerAllocateRequest(
                            devicesIDs=["accel9"])]))
            assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "accel9" in err.value.details()


def test_allocate_unhealthy_device_fails(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    mgr = make_manager(node4)
    mgr.set_device_health("accel2", api.UNHEALTHY)
    with ServingManager(mgr, plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            with pytest.raises(grpc.RpcError) as err:
                stub.Allocate(api.v1beta1_pb2.AllocateRequest(
                    container_requests=[
                        api.v1beta1_pb2.ContainerAllocateRequest(
                            devicesIDs=["accel2"])]))
            assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "unhealthy" in err.value.details()


def test_health_change_streams_update(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    mgr = make_manager(node4)
    with ServingManager(mgr, plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            stream = iter(stub.ListAndWatch(api.v1beta1_pb2.Empty()))
            first = next(stream)
            assert all(d.health == api.HEALTHY for d in first.devices)
            mgr.set_device_health("accel1", api.UNHEALTHY)
            second = next(stream)
            by_id = {d.ID: d.health for d in second.devices}
            assert by_id["accel1"] == api.UNHEALTHY
            assert by_id["accel0"] == api.HEALTHY


def test_hotplug_discovered_while_serving(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    mgr = make_manager(node4)
    with ServingManager(mgr, plugin_dir):
        node4.add_chip(4)
        node4.add_chip(5)
        node4.set_topology("2x3")
        deadline = time.time() + 10
        while time.time() < deadline:
            if "accel5" in mgr.list_devices():
                break
            time.sleep(0.1)
        assert "accel5" in mgr.list_devices()
        # The serve loop re-serves on a fresh socket; the new device
        # must be allocatable there (beta_plugin_test.go:132-147).
        assert mgr.wait_until_serving(10)
        specs = mgr.device_specs("accel5")
        assert specs[0].host_path == os.path.join(node4.dev_dir, "accel5")


def test_get_preferred_allocation_topology_compact(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    with ServingManager(make_manager(node4), plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            resp = stub.GetPreferredAllocation(
                api.v1beta1_pb2.PreferredAllocationRequest(
                    container_requests=[
                        api.v1beta1_pb2.ContainerPreferredAllocationRequest(
                            available_deviceIDs=[
                                "accel0", "accel1", "accel2", "accel3"],
                            allocation_size=2)]))
            chosen = list(resp.container_responses[0].deviceIDs)
            # On a 2x2 torus any 2 chips sharing an axis form a 1x2
            # box; chips 0,1 share x in row-major layout.
            assert chosen == ["accel0", "accel1"]


def test_kubelet_restart_triggers_reserve(node4, fast_intervals):
    plugin_dir = short_tmpdir()
    mgr = make_manager(node4)
    with ServingManager(mgr, plugin_dir) as sm:
        first_sock = sm.socket_path()
        # Simulate kubelet restart wiping the device-plugin dir.
        os.unlink(first_sock)
        deadline = time.time() + 10
        second_sock = None
        while time.time() < deadline:
            socks = [f for f in os.listdir(plugin_dir)
                     if f.startswith("tpu-") and f.endswith(".sock")]
            if socks and os.path.join(plugin_dir, socks[0]) != first_sock:
                second_sock = os.path.join(plugin_dir, socks[0])
                break
            time.sleep(0.1)
        assert second_sock is not None, "plugin did not re-serve"
        with grpc.insecure_channel(f"unix://{second_sock}") as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            opts = stub.GetDevicePluginOptions(api.v1beta1_pb2.Empty())
            assert opts.get_preferred_allocation_available


def test_allocate_chip_vanished_is_invalid_argument(node4,
                                                    fast_intervals):
    """Hot-unplug race: a device passes the health gate but its chip
    leaves the backend before the coord read (stress-suite find).
    The Allocate error contract must hold — KeyError mapped to
    INVALID_ARGUMENT, never a raw backend error surfacing as
    UNKNOWN."""
    from container_engine_accelerators_tpu.chip import (
        ChipBackendError,
    )

    manager = make_manager(node4)
    try:
        # Simulate the interleaving deterministically: the health map
        # still lists accel3 but the backend no longer knows chip 3.
        del manager._backend._coords[3]
        with pytest.raises(KeyError, match="vanished"):
            manager.allocate_envs(["accel3"])
        # Preference is advisory: same race falls back to first-N
        # instead of raising.
        got = manager.preferred_allocation(
            ["accel0", "accel1", "accel3"], ["accel1"], 2)
        assert got == ["accel1", "accel0"]
        # The raw backend error shape never escapes either call.
        for fn in (lambda: manager.allocate_envs(["accel3"]),
                   lambda: manager.preferred_allocation(
                       ["accel3"], [], 1)):
            try:
                fn()
            except ChipBackendError:
                pytest.fail("raw ChipBackendError escaped")
            except KeyError:
                pass
    finally:
        manager.stop()
