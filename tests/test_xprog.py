# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""IR-level program hygiene: facts, rules, and the golden manifest.

The tier-1 half of `make program-check`: the registered hot programs
(dense + paged engine trios, parallel train step) must show zero IR
findings and fingerprint-match the committed PROGRAM_MANIFEST.json;
the seeded IR fixtures must fire EXPECT-exact; and a deliberately
dropped ``donate_argnums`` on the paged step program must fail BOTH
the donation-miss rule and the manifest diff (ISSUE 10 acceptance).
"""

import json
import os
import sys

import pytest

from container_engine_accelerators_tpu.analysis import xprog
from tests.conftest import REPO_ROOT

_TOOLS = os.path.join(REPO_ROOT, "tools")
if _TOOLS not in sys.path:
    sys.path.append(_TOOLS)  # append: tools/ must not shadow imports
import program_manifest  # noqa: E402

MANIFEST = os.path.join(REPO_ROOT, "PROGRAM_MANIFEST.json")
FIXTURE_DIR = os.path.join("tests", "fixtures", "analysis")
FIXTURE = os.path.join(FIXTURE_DIR, "xprog_fixture.py")


@pytest.fixture(scope="module")
def registry():
    """The real hot-program registry — built once (it compiles the
    canonical example engines/trainer)."""
    return xprog.default_registry()


@pytest.fixture(scope="module")
def registry_facts(registry):
    return xprog.registry_facts(registry)


# -- the tree is clean ------------------------------------------------


def test_registry_names_the_hot_program_set(registry):
    assert sorted(s.name for s in registry) == [
        "engine.dense_draft", "engine.dense_draft_insert",
        "engine.dense_insert", "engine.dense_prefill",
        "engine.dense_step", "engine.dense_verify",
        "engine.paged_draft", "engine.paged_draft_insert",
        "engine.paged_hydrate", "engine.paged_insert",
        "engine.paged_int4_insert", "engine.paged_int4_prefill",
        "engine.paged_int4_step", "engine.paged_int8_insert",
        "engine.paged_int8_prefill", "engine.paged_int8_step",
        "engine.paged_prefill", "engine.paged_step",
        "engine.paged_verify", "engine.windowed_prefill",
        "engine.windowed_step", "train.step"]


def test_tree_programs_have_zero_ir_findings(registry,
                                             registry_facts):
    """The tier-1 drift gate: donation masks intact, no captured
    constants, no host callbacks, no weak-type inputs in any
    registered hot program."""
    findings = []
    for spec in registry:
        findings.extend(
            xprog.check_facts(registry_facts[spec.name], spec,
                              root=REPO_ROOT))
    assert findings == [], "\n".join(f.format() for f in findings)


def test_manifest_matches_tree(registry, registry_facts):
    """The committed golden manifest re-derives cleanly — donation,
    avals, callbacks, consts exact; FLOPs/bytes within tolerance."""
    with open(MANIFEST) as f:
        committed = json.load(f)
    derived = {
        "platform": committed.get("platform"),
        "programs": {name: xprog.manifest_entry(facts, root=REPO_ROOT)
                     for name, facts in registry_facts.items()},
    }
    problems = xprog.diff_manifest(committed, derived)
    assert problems == [], "\n".join(problems) + (
        "\n(intentional change? re-derive: JAX_PLATFORMS=cpu "
        "python tools/program_manifest.py --update)")


def test_known_facts_of_the_registered_set(registry_facts):
    """Spot-checks that the facts mean what the manifest claims."""
    step = registry_facts["engine.paged_step"]
    # donate_argnums=(2,3,4,5): the cache tree + row state donate;
    # params do not.
    donated = [e for e in step.inputs if e["donated"]]
    assert donated, "paged step donates its cache/state"
    # The params tree never donates (embedding et al. are reused by
    # every program); the donated set is cache + per-row state.
    assert all("embedding" not in e["path"] for e in donated)
    assert any("cached_key" in e["path"] for e in donated)
    assert step.callbacks == ()
    assert step.consts_large == ()
    assert all(not e["weak_type"] for e in step.inputs)
    train = registry_facts["train.step"]
    # donate_state=True: every state leaf donates, the batch does not.
    undonated = [e for e in train.inputs if not e["donated"]]
    assert len(undonated) == 2            # (tokens, labels)
    assert train.flops and train.flops > 0


# -- seeded violations ------------------------------------------------


def test_ir_fixtures_fire_exactly_as_seeded():
    """Shared with `make analysis-check`: every seeded IR violation
    under the fixture DIRECTORY fires at its EXPECT line and nowhere
    else (the directory walk also errors on an IR EXPECT in a file
    with no fixture_specs — a violation nothing would verify)."""
    missing, unexpected = xprog.verify_fixtures(FIXTURE_DIR,
                                                root=REPO_ROOT)
    assert missing == [], f"seeded IR violations did not fire: " \
                          f"{missing}"
    assert unexpected == [], f"unexpected IR findings: {unexpected}"


def test_ir_expect_without_fixture_specs_is_an_error(tmp_path):
    """A seeded IR violation in a file the verifier cannot load
    would be verified by nothing — the directory walk must error,
    not skip."""
    orphan = tmp_path / "orphan_fixture.py"
    orphan.write_text(
        "import jax\n\n\n"
        "@jax.jit  # EXPECT: donation-miss\n"
        "def unverified(cache):\n"
        "    return cache * 2\n")
    with pytest.raises(ValueError, match="fixture_specs"):
        xprog.verify_fixtures(str(tmp_path), root=REPO_ROOT)


def test_dropped_donation_fails_rule_and_manifest(registry,
                                                  registry_facts):
    """ISSUE 10 acceptance: deliberately re-jit the paged step with
    its ``donate_argnums`` dropped — the donation-miss rule must
    fire AND the manifest diff must flag the drift."""
    import jax

    from container_engine_accelerators_tpu.models import decode

    spec = next(s for s in registry if s.name == "engine.paged_step")
    undonated = jax.jit(decode._paged_step_impl.__wrapped__,
                        static_argnames=("model",))
    bad = xprog.HotProgram("engine.paged_step", undonated,
                           spec.args, spec.kwargs)
    facts = xprog.program_facts(bad)
    findings = xprog.check_facts(facts, bad, root=REPO_ROOT)
    rules = {f.rule for f in findings}
    assert "donation-miss" in rules, [f.format() for f in findings]
    # The finding anchors at the real program's decorator line.
    assert all(f.path.endswith("models/decode.py")
               for f in findings)

    with open(MANIFEST) as f:
        committed = json.load(f)
    derived = {
        "platform": committed.get("platform"),
        "programs": {
            **{name: xprog.manifest_entry(fct, root=REPO_ROOT)
               for name, fct in registry_facts.items()},
            "engine.paged_step": xprog.manifest_entry(facts,
                                                   root=REPO_ROOT),
        },
    }
    problems = xprog.diff_manifest(committed, derived)
    assert any("engine.paged_step" in p and "donated" in p
               for p in problems), problems


# -- manifest diff mechanics ------------------------------------------


def _mini_manifest():
    return {
        "platform": "cpu",
        "programs": {
            "p": {"digest": "abc", "donated_count": 1,
                  "inputs": [], "outputs": [], "callbacks": [],
                  "upcasts": 0, "anchor": "x.py",
                  "consts": {"count": 0, "bytes": 0, "large": []},
                  "cost": {"flops": 1000.0,
                           "bytes_accessed": 500.0}},
        },
    }


def test_diff_flags_cost_drift_beyond_tolerance():
    old = _mini_manifest()
    new = _mini_manifest()
    new["programs"]["p"]["cost"]["flops"] = 1090.0   # 9%: inside
    assert xprog.diff_manifest(old, new) == []
    new["programs"]["p"]["cost"]["flops"] = 1200.0   # 20%: drift
    problems = xprog.diff_manifest(old, new)
    assert any("flops" in p for p in problems)


def test_diff_flags_program_set_changes():
    old = _mini_manifest()
    new = _mini_manifest()
    new["programs"]["q"] = dict(new["programs"]["p"])
    problems = xprog.diff_manifest(old, new)
    assert any("unexpected new program" in p for p in problems)
    problems = xprog.diff_manifest(new, old)
    assert any("no longer registered" in p for p in problems)


# -- the update workflow ----------------------------------------------


def test_manifest_update_round_trips_to_clean_check(tmp_path):
    """`--update` writes a manifest that `--check` immediately
    accepts (ISSUE 10 satellite: the update workflow round-trips to
    a clean diff); a doctored manifest then fails the check."""
    manifest = str(tmp_path / "manifest.json")
    registry = os.path.join(REPO_ROOT, FIXTURE) + ":clean_specs"
    rc = program_manifest.main(
        ["--registry", registry, "--manifest", manifest, "--update"])
    assert rc == 0
    rc = program_manifest.main(
        ["--registry", registry, "--manifest", manifest, "--check"])
    assert rc == 0
    with open(manifest) as f:
        data = json.load(f)
    data["programs"]["fixture.clean_step"]["cost"]["flops"] = 1e12
    with open(manifest, "w") as f:
        json.dump(data, f)
    rc = program_manifest.main(
        ["--registry", registry, "--manifest", manifest, "--check"])
    assert rc == 1


def test_update_refuses_live_ir_findings(tmp_path):
    """A violating registry cannot be baked into a golden manifest."""
    manifest = str(tmp_path / "manifest.json")
    registry = os.path.join(REPO_ROOT, FIXTURE) + ":fixture_specs"
    rc = program_manifest.main(
        ["--registry", registry, "--manifest", manifest, "--update"])
    assert rc == 1
    assert not os.path.exists(manifest)
