# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""partition_tpu CLI tests (mirrors partition_gpu_test.go's table style)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "cmd"))

import partition_tpu  # noqa: E402

from container_engine_accelerators_tpu.chip import (  # noqa: E402
    BadShapeError,
    NonUniformPartitionError,
    PyChipBackend,
)


@pytest.fixture
def node8(fake_node):
    for i in range(8):
        fake_node.add_chip(i)
    fake_node.set_topology("2x4")
    return fake_node


def backend_for(node):
    b = PyChipBackend()
    b.init(node.dev_dir, node.state_dir)
    return b


@pytest.mark.parametrize("shape,expect", [
    ("2x2", {"tpu-2x2-0": [0, 1, 4, 5], "tpu-2x2-1": [2, 3, 6, 7]}),
    ("2x4", {"tpu-2x4-0": [0, 1, 2, 3, 4, 5, 6, 7]}),
    ("1x1", {f"tpu-1x1-{i}": [c] for i, c in enumerate(
        [0, 1, 2, 3, 4, 5, 6, 7])}),
])
def test_build_partition_plan(node8, shape, expect):
    plan = partition_tpu.build_partition_plan(backend_for(node8), shape)
    # 1x1 slice order is row-major over tiles, not chip order; compare
    # as sets of chip groups plus exact ids for the 2x2 case.
    assert {tuple(v) for v in plan.values()} == \
        {tuple(v) for v in expect.values()}
    if shape == "2x2":
        assert plan == expect


@pytest.mark.parametrize("shape,err", [
    ("2x3", NonUniformPartitionError),
    ("garbage", BadShapeError),
])
def test_build_partition_plan_errors(node8, shape, err):
    with pytest.raises(err):
        partition_tpu.build_partition_plan(backend_for(node8), shape)


def write_config(tmp_path, body):
    p = tmp_path / "tpu_config.json"
    p.write_text(body)
    return str(p)


def test_main_publishes_plan(node8, tmp_path):
    cfg_file = write_config(tmp_path, '{"tpuPartitionSize": "2x2"}')
    rc = partition_tpu.main(["--config-file", cfg_file,
                             "--device-dir", node8.dev_dir,
                             "--state-dir", node8.state_dir])
    assert rc == 0
    plan = json.load(open(os.path.join(node8.state_dir, "partitions.json")))
    assert plan["shape"] == "2x2"
    assert plan["topology"] == "2x4x1"
    assert plan["slices"]["tpu-2x2-1"] == [2, 3, 6, 7]


def test_main_no_config_is_noop(node8, tmp_path):
    rc = partition_tpu.main(["--config-file", str(tmp_path / "none.json"),
                             "--device-dir", node8.dev_dir,
                             "--state-dir", node8.state_dir])
    assert rc == 0
    assert not os.path.exists(os.path.join(node8.state_dir,
                                           "partitions.json"))


def test_main_invalid_shape_fails(node8, tmp_path):
    cfg_file = write_config(tmp_path, '{"tpuPartitionSize": "3x3"}')
    rc = partition_tpu.main(["--config-file", cfg_file,
                             "--device-dir", node8.dev_dir,
                             "--state-dir", node8.state_dir])
    assert rc == 1


def test_main_no_chips_fails(fake_node, tmp_path):
    cfg_file = write_config(tmp_path, '{"tpuPartitionSize": "1x1"}')
    rc = partition_tpu.main(["--config-file", cfg_file,
                             "--device-dir", fake_node.dev_dir,
                             "--state-dir", fake_node.state_dir])
    assert rc == 1


def test_main_clean(node8, tmp_path):
    cfg_file = write_config(tmp_path, '{"tpuPartitionSize": "2x2"}')
    partition_tpu.main(["--config-file", cfg_file,
                        "--device-dir", node8.dev_dir,
                        "--state-dir", node8.state_dir])
    rc = partition_tpu.main(["--clean", "--state-dir", node8.state_dir])
    assert rc == 0
    assert not os.path.exists(os.path.join(node8.state_dir,
                                           "partitions.json"))
