# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The affinity-key contract: serving/affinity.py computes the SAME
content-chain keys the engine's paged block pool indexes blocks by.
The router steers on these keys from a jax-free process, so any drift
between the two implementations silently turns every affinity hit
into a miss — these tests pin the byte-identity against both an
explicit sha256 recomputation and a real ``_BlockPool``'s registered
index."""

import hashlib

import numpy as np

from container_engine_accelerators_tpu.models.decode import _BlockPool
from container_engine_accelerators_tpu.serving.affinity import (
    DEFAULT_BLOCK_SIZE,
    KV_BLOCK_ENV,
    affinity_key,
    chain_digest,
    default_block_size,
    full_block_keys,
    partial_key,
)

BS = 4


def _sha(prev, *chunks):
    h = hashlib.sha256(b"" if prev is None else prev)
    for c in chunks:
        h.update(c)
    return h.digest()


def _tok_bytes(tokens):
    return np.asarray(tokens, np.int64).tobytes()


# ---------------------------------------------------------------------------
# chain_digest against an explicit recomputation
# ---------------------------------------------------------------------------


def test_chain_digest_matches_explicit_sha256():
    b0 = chain_digest(None, (5, 6, 7, 8))
    assert b0 == _sha(None, _tok_bytes([5, 6, 7, 8]))
    b1 = chain_digest(b0, (1, 2, 3, 4))
    assert b1 == _sha(b0, _tok_bytes([1, 2, 3, 4]))
    # Order matters: the chain is positional, not a token multiset.
    assert chain_digest(None, (6, 5, 7, 8)) != b0


def test_partial_tag_prevents_full_partial_collision():
    full = chain_digest(None, (9, 9, 9, 9))
    part = chain_digest(None, ("partial", (9, 9, 9, 9)))
    assert full != part
    assert part == _sha(None, b"partial", _tok_bytes([9, 9, 9, 9]))
    assert partial_key(None, (9, 9, 9, 9)) == part
    # Chained partials hash the previous link too.
    assert partial_key(full, (1,)) \
        == _sha(full, b"partial", _tok_bytes([1]))


def test_full_block_keys_chain_each_other():
    tokens = list(range(1, 13))   # three BS=4 blocks
    keys = full_block_keys(tokens, BS)
    assert len(keys) == 3
    chain = None
    for i, key in enumerate(keys):
        chain = _sha(chain, _tok_bytes(tokens[i * BS:(i + 1) * BS]))
        assert key == chain


# ---------------------------------------------------------------------------
# byte-parity with the engine's block pool
# ---------------------------------------------------------------------------


def test_register_indexes_exactly_the_hoisted_keys():
    pool = _BlockPool(num_blocks=8, block_size=BS)
    tokens = [5, 6, 7, 8, 1, 2, 3, 4, 9, 9]
    pool.register(tokens, plen=10, block_of_index=[0, 1, 2])
    keys = full_block_keys(tokens[:8], BS)
    assert pool._index[keys[0]] == 0
    assert pool._index[keys[1]] == 1
    # The prompt-tail partial block indexes every leading-prefix key.
    for q in (1, 2):
        assert pool._index[partial_key(keys[-1], tokens[8:8 + q])] == 2
    assert len(pool._index) == 2 + 2


def test_lookup_walks_the_same_chain():
    pool = _BlockPool(num_blocks=8, block_size=BS)
    tokens = [5, 6, 7, 8, 1, 2, 3, 4]
    pool.register(tokens, plen=8, block_of_index=[0, 1])
    shared, sources, fork = pool.lookup(tokens + [40], count=False)
    assert (shared, sources, fork) == (8, [("dev", 0), ("dev", 1)],
                                       None)
    # The router's placement key IS the last link lookup() walked to.
    assert affinity_key(tokens, BS) == full_block_keys(tokens, BS)[-1]
    # A prompt diverging inside the covered region maps elsewhere.
    other = [5, 6, 7, 8, 1, 2, 3, 40]
    assert affinity_key(other, BS) != affinity_key(tokens, BS)
    assert pool.lookup(other + [41], count=False)[0] == 4


# ---------------------------------------------------------------------------
# affinity_key semantics
# ---------------------------------------------------------------------------


def test_affinity_key_none_below_one_block():
    assert affinity_key([1, 2, 3], BS) is None
    assert affinity_key([], BS) is None
    assert affinity_key([1, 2, 3, 4], BS) is not None


def test_affinity_key_caps_at_max_blocks():
    shared = [7] * (3 * BS)
    a = shared + [1, 2, 3, 4]
    b = shared + [5, 6, 7, 8]
    # Uncapped, the fourth (divergent) block splits the keys...
    assert affinity_key(a, BS) != affinity_key(b, BS)
    # ...capped at the pinned region, both steer to one engine.
    assert affinity_key(a, BS, max_blocks=3) \
        == affinity_key(b, BS, max_blocks=3) \
        == full_block_keys(shared, BS)[-1]
    # Trailing sub-block tokens never change the key.
    assert affinity_key(a + [9, 9], BS, max_blocks=3) \
        == affinity_key(a, BS, max_blocks=3)


def test_default_block_size_reads_the_engine_knob(monkeypatch):
    monkeypatch.delenv(KV_BLOCK_ENV, raising=False)
    assert default_block_size() == DEFAULT_BLOCK_SIZE
    monkeypatch.setenv(KV_BLOCK_ENV, "4")
    assert default_block_size() == 4
