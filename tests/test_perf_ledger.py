# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The continuous perf ledger's contract (tools/perf_ledger.py).

Every speed claim this repo makes is supposed to be machine-verified
against its own history: one schema-validated writer, rig-
fingerprinted rows, a direction-aware 10% regression gate with an
explicit accept path, cross-rig comparison REFUSED (the
promote_artifact posture), and wedged-rig windows recorded as
``skipped_unmeasurable`` — never as zero-valued regressions. These
tests pin each of those behaviors on hand-built series, plus the
acceptance triple for ``make perf-check`` itself: pass on a fresh
same-rig window, fail (metric named, both rows printed) on a
doctored >10% rows/step drop or TTFT p99 inflation, documented-skip
when only foreign-rig baselines exist.
"""

import json
import os
import subprocess
import sys

import pytest

from tests.conftest import REPO_ROOT

_TOOLS = os.path.join(REPO_ROOT, "tools")
if _TOOLS not in sys.path:
    sys.path.append(_TOOLS)  # append, not insert: tools/ modules
    # must never shadow the package/test import namespace.
import artifact_freshness  # noqa: E402
import perf_ledger  # noqa: E402
import perf_report  # noqa: E402

RIG_A = {"platform": "cpu", "device_kind": "cpu", "device_count": 8,
         "jax_version": "0.4.37", "knobs": {}}
RIG_B = {"platform": "tpu", "device_kind": "TPU v5 lite",
         "device_count": 1, "jax_version": "0.4.37", "knobs": {}}
RIG_A_KNOBBED = dict(RIG_A, knobs={"CEA_TPU_KV_BLOCK": "32"})


def _append(path, source, metrics, rig=RIG_A, **kw):
    return perf_ledger.append_row(path, source, metrics,
                                  fingerprint=rig, devices=[], **kw)


def _check(path, **kw):
    lines = []
    failures, skips = perf_ledger.run_check(path, out=lines.append,
                                            **kw)
    return failures, skips, "\n".join(lines)


# ---------------------------------------------------------------------------
# Writer / schema
# ---------------------------------------------------------------------------


def test_append_round_trip_schema_exact(tmp_path):
    path = str(tmp_path / "L.json")
    row = _append(path, "paging_check",
                  {"sustained_rows_ratio": 2.49, "rows_per_step": 10.0},
                  config={"kv_block_size": 4}, note="first window")
    doc = perf_ledger.load_ledger(path)
    assert perf_ledger.validate_doc(doc) == []
    assert doc["schema_version"] == perf_ledger.SCHEMA_VERSION
    (loaded,) = doc["rows"]
    assert loaded == row
    assert loaded["source"] == "paging_check"
    assert loaded["status"] == "measured"
    assert loaded["metrics"] == {"sustained_rows_ratio": 2.49,
                                 "rows_per_step": 10.0}
    assert loaded["fingerprint"] == RIG_A
    assert loaded["config"] == {"kv_block_size": 4}
    prov = loaded["provenance"]
    import datetime
    datetime.datetime.fromisoformat(prov["generated_utc"])
    assert prov["git_sha"]
    # The append is journaled through the shared writer.
    from container_engine_accelerators_tpu import obs
    events = [e for e in obs.TRACER.snapshot()["events"]
              if e["name"] == "perf.ledger_append"
              and e["fields"].get("source") == "paging_check"]
    assert events, "perf.ledger_append event not journaled"


def test_writer_refuses_nonconforming_rows(tmp_path):
    path = str(tmp_path / "L.json")
    # Unregistered metric name: an ungated number is a narrated one.
    with pytest.raises(perf_ledger.LedgerError,
                       match="no registered direction"):
        _append(path, "x", {"made_up_series": 1.0})
    # Non-finite values can never be compared.
    with pytest.raises(perf_ledger.LedgerError, match="finite"):
        _append(path, "x", {"rows_per_step": float("nan")})
    assert not os.path.exists(path)  # nothing landed


def test_bad_and_legacy_rows_rejected_field_level(tmp_path):
    path = str(tmp_path / "L.json")
    _append(path, "paging_check", {"rows_per_step": 10.0})
    doc = perf_ledger.load_ledger(path)
    # Doctor a legacy/corrupt shape straight into the file (tests may;
    # tree code may not — the ledger-writer lint rule).
    doc["rows"].append({"source": "paging_check", "status": "ok",
                        "metrics": {"rows_per_step": "fast"},
                        "fingerprint": {"platform": "cpu"},
                        "speed": "very yes"})
    with open(path, "w") as f:
        json.dump(doc, f)
    problems = perf_ledger.validate_doc(perf_ledger.load_ledger(path))
    text = "\n".join(problems)
    assert "rows[1].status" in text
    assert "rows[1].metrics.rows_per_step" in text
    assert "rows[1].fingerprint.device_count" in text
    assert "rows[1].provenance" in text
    assert "rows[1].speed: unexpected field" in text
    # The gate refuses the whole file, naming the fields.
    failures, _, out = _check(path)
    assert failures and "rows[1].status" in out
    # And the writer refuses to append onto a bad ledger.
    with pytest.raises(perf_ledger.LedgerError,
                       match="non-conforming ledger"):
        _append(path, "paging_check", {"rows_per_step": 9.9})


def test_metric_direction_resolution():
    assert perf_ledger.metric_direction("rows_per_step") == "up"
    # Longest-prefix: per-batch suffixes inherit the base direction.
    assert perf_ledger.metric_direction(
        "decode_tokens_per_sec_b8") == "up"
    assert perf_ledger.metric_direction("ms_per_token_b1") == "down"
    assert perf_ledger.metric_direction("ttft_p99_ms") == "down"
    # tflops (rate, up) does not collide with flops (cost, down).
    assert perf_ledger.metric_direction("tflops_dense") == "up"
    assert perf_ledger.metric_direction(
        "flops:engine.paged_step") == "down"
    with pytest.raises(perf_ledger.LedgerError):
        perf_ledger.metric_direction("unheard_of_number")


# ---------------------------------------------------------------------------
# Gate math
# ---------------------------------------------------------------------------


def test_direction_aware_ten_percent_gate_math(tmp_path):
    base = {"metrics": {"rows_per_step": 100.0, "ttft_p99_ms": 100.0},
            "fingerprint": RIG_A}
    # Throughput down 11% AND latency up 11%: both named.
    bad = {"metrics": {"rows_per_step": 89.0, "ttft_p99_ms": 111.0},
           "fingerprint": RIG_A}
    found = {r["metric"]: r for r in perf_ledger.regressions(bad, base)}
    assert set(found) == {"rows_per_step", "ttft_p99_ms"}
    assert found["rows_per_step"]["direction"] == "up"
    assert found["ttft_p99_ms"]["direction"] == "down"
    assert abs(found["rows_per_step"]["regression"] - 0.11) < 1e-9
    # 9% either way is inside tolerance.
    ok = {"metrics": {"rows_per_step": 91.0, "ttft_p99_ms": 109.0},
          "fingerprint": RIG_A}
    assert perf_ledger.regressions(ok, base) == []
    # Improvements never fire, in either direction.
    better = {"metrics": {"rows_per_step": 200.0, "ttft_p99_ms": 10.0},
              "fingerprint": RIG_A}
    assert perf_ledger.regressions(better, base) == []
    # Latency IMPROVING 11% must not fire the up-rule and vice versa.
    flipped = {"metrics": {"rows_per_step": 111.0,
                           "ttft_p99_ms": 89.0},
               "fingerprint": RIG_A}
    assert perf_ledger.regressions(flipped, base) == []


def test_cross_rig_comparison_refused(tmp_path):
    cur = {"metrics": {"rows_per_step": 1.0}, "fingerprint": RIG_A}
    base = {"metrics": {"rows_per_step": 100.0}, "fingerprint": RIG_B}
    with pytest.raises(perf_ledger.CrossRigError,
                       match="refusing cross-rig"):
        perf_ledger.regressions(cur, base)
    # A knob change alone is a different rig too: the measurement's
    # meaning changed even on identical hardware.
    base_knobbed = {"metrics": {"rows_per_step": 100.0},
                    "fingerprint": RIG_A_KNOBBED}
    with pytest.raises(perf_ledger.CrossRigError):
        perf_ledger.regressions(cur, base_knobbed)


def test_no_same_rig_baseline_is_documented_skip(tmp_path):
    path = str(tmp_path / "L.json")
    _append(path, "paging_check", {"rows_per_step": 100.0}, rig=RIG_B)
    _append(path, "paging_check", {"rows_per_step": 1.0}, rig=RIG_A)
    failures, skips, out = _check(path)
    # A 99% "regression" across rigs: refused, skipped, DOCUMENTED —
    # once per (source, rig) series, since the gate walks series.
    assert failures == []
    assert skips == ["paging_check", "paging_check"]
    assert "no same-rig baseline" in out
    assert "foreign-rig" in out
    assert "SKIP" in out  # printed, not silent


def test_skipped_unmeasurable_rows_are_no_data(tmp_path):
    path = str(tmp_path / "L.json")
    _append(path, "paging_check", {"rows_per_step": 100.0})
    _append(path, "paging_check", {}, status="skipped_unmeasurable",
            note="backend probe hung (limit 180s)")
    # Newest row is a skip: no data — NOT a 100 -> 0 regression.
    failures, skips, out = _check(path)
    assert failures == [] and skips == ["paging_check"]
    assert "skipped_unmeasurable" in out and "no data" in out
    # A later measured row baselines against the last MEASURED row,
    # straight through the skip.
    _append(path, "paging_check", {"rows_per_step": 50.0})
    failures, _, out = _check(path)
    assert failures == ["paging_check"]
    assert "rows_per_step regressed 50.0%" in out
    # And a measured skip-value of zero is impossible by schema: a
    # skipped row carrying metrics is rejected.
    doc = perf_ledger.load_ledger(path)
    doc["rows"][1]["metrics"] = {"rows_per_step": 0.0}
    with open(path, "w") as f:
        json.dump(doc, f)
    problems = perf_ledger.validate_doc(perf_ledger.load_ledger(path))
    assert any("measured nothing" in p for p in problems)


def test_unaccepted_regression_never_becomes_baseline(tmp_path):
    """The slow-decay guarantee: the baseline anchors at the
    last-known-good level, so a regression cannot launder itself in
    by recurring — and an 8%-per-window stepwise decay fails the
    moment its CUMULATIVE drop from the anchored baseline crosses
    10%, even though each window-to-window step stays under
    tolerance."""
    path = str(tmp_path / "L.json")
    _append(path, "paging_check", {"rows_per_step": 10.0})
    _append(path, "paging_check", {"rows_per_step": 8.0})
    failures, _, _ = _check(path)
    assert failures == ["paging_check"]
    # The same regressed level again: STILL fails vs the anchored
    # 10.0 (pre-fix, the first failing window became the baseline
    # and the regression self-healed).
    _append(path, "paging_check", {"rows_per_step": 8.0})
    failures, _, out = _check(path)
    assert failures == ["paging_check"]
    assert "(10.0 -> 8.0" in out
    # Stepwise decay under per-window tolerance: 10.0 -> 9.3 (7%,
    # becomes baseline) -> 8.6 vs 9.3 is 7.5% (passes, anchors) ->
    # 8.0 vs 8.6 is 7% but... each clean window re-anchors, so pure
    # sub-tolerance decay is the accepted residual risk; what CANNOT
    # happen is a >10% drop anchoring itself without accept.
    path2 = str(tmp_path / "L2.json")
    _append(path2, "serving_bench", {"ttft_p99_ms": 100.0})
    _append(path2, "serving_bench", {"ttft_p99_ms": 115.0})  # +15%
    _append(path2, "serving_bench", {"ttft_p99_ms": 115.0})
    failures, _, _ = _check(path2)
    assert failures == ["serving_bench"]  # still vs the 100.0 anchor
    # Recovery without accept: dropping back under tolerance of the
    # anchor clears the gate naturally.
    _append(path2, "serving_bench", {"ttft_p99_ms": 104.0})
    failures, _, _ = _check(path2)
    assert failures == []


def test_newer_foreign_or_skip_rows_never_shadow_a_regression(
        tmp_path):
    """The laundering side-door: an unaccepted same-rig regression
    must keep failing even when a NEWER row lands for the source
    from a different rig, or as a same-rig skipped_unmeasurable —
    the gate walks every (source, rig) series, so neither shadows
    it green."""
    path = str(tmp_path / "L.json")
    _append(path, "paging_check", {"rows_per_step": 10.0})
    _append(path, "paging_check", {"rows_per_step": 5.0})
    # A CPU smoke row lands afterwards (different rig)...
    _append(path, "paging_check", {"rows_per_step": 3.0}, rig=RIG_B)
    failures, _, out = _check(path)
    assert failures == ["paging_check"]  # the RIG_A 10 -> 5 still gates
    assert "(10.0 -> 5.0" in out
    # ...and a same-rig skip row doesn't clear it either: both the
    # no-data note AND the standing failure are reported.
    _append(path, "paging_check", {}, status="skipped_unmeasurable",
            note="window lost")
    failures, _, out = _check(path)
    assert failures == ["paging_check"]
    assert "no data" in out and "(10.0 -> 5.0" in out


def test_vanished_gated_metric_fails(tmp_path):
    """A gated metric that silently disappears from the newest row
    is a regression (the series would otherwise vanish with every
    gate green); accept is the documented retirement path."""
    path = str(tmp_path / "L.json")
    _append(path, "spill_check", {"spill_goodput_ratio": 1.19,
                                  "kv_spill_hit_rate": 0.4})
    _append(path, "spill_check", {"spill_goodput_ratio": 1.20})
    failures, _, out = _check(path)
    assert failures == ["spill_check"]
    assert "kv_spill_hit_rate vanished" in out
    # And the narrowed row did not anchor: a third narrow row still
    # fails against the full baseline...
    _append(path, "spill_check", {"spill_goodput_ratio": 1.20})
    failures, _, _ = _check(path)
    assert failures == ["spill_check"]
    # ...until the retirement is accepted.
    perf_ledger.main(["accept", "--ledger", path, "--source",
                      "spill_check", "--note", "metric retired"])
    failures, _, _ = _check(path)
    assert failures == []


def test_accept_rig_filter(tmp_path, capsys):
    """With multi-rig history, accept pins the intended series via
    --rig and always reports WHICH rig it blessed."""
    path = str(tmp_path / "L.json")
    _append(path, "paging_check", {"rows_per_step": 10.0})
    _append(path, "paging_check", {"rows_per_step": 5.0})
    _append(path, "paging_check", {"rows_per_step": 3.0}, rig=RIG_B)
    # --rig pins the cpu series even though the tpu row is newer.
    rc = perf_ledger.main(["accept", "--ledger", path, "--source",
                           "paging_check", "--note", "cpu retune",
                           "--rig", "cpu:"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "on cpu:" in out  # the blessed rig is visible
    rows = perf_ledger.load_ledger(path)["rows"]
    assert rows[1].get("accepted") and not rows[2].get("accepted")
    # A filter matching no rig names the rigs it saw.
    with pytest.raises(perf_ledger.LedgerError, match="rigs seen"):
        perf_ledger.accept_newest(path, "paging_check", "x",
                                  rig="v9000")


def test_accept_path_blesses_new_baseline(tmp_path):
    path = str(tmp_path / "L.json")
    _append(path, "paging_check", {"rows_per_step": 100.0})
    _append(path, "paging_check", {"rows_per_step": 50.0})
    failures, _, out = _check(path)
    assert failures == ["paging_check"]
    assert "perf_ledger.py accept" in out  # the hint is printed
    rc = perf_ledger.main(["accept", "--ledger", path,
                           "--source", "paging_check",
                           "--note", "engine rewrite, see PR"])
    assert rc == 0
    failures, _, out = _check(path)
    assert failures == [] and "accepted as the new baseline" in out
    # The accepted level IS the next window's baseline.
    _append(path, "paging_check", {"rows_per_step": 48.0})
    failures, _, _ = _check(path)
    assert failures == []  # within 10% of the accepted 50
    _append(path, "paging_check", {"rows_per_step": 40.0})
    failures, _, _ = _check(path)
    assert failures == ["paging_check"]


# ---------------------------------------------------------------------------
# CLI acceptance triple (the `make perf-check` behaviors)
# ---------------------------------------------------------------------------


def test_cli_acceptance_triple(tmp_path, capsys):
    path = str(tmp_path / "L.json")
    # 1. Freshly appended same-rig window: passes.
    _append(path, "paging_check", {"rows_per_step": 10.0,
                                   "sustained_rows_ratio": 2.49})
    _append(path, "serving_bench", {"ttft_p99_ms": 200.0})
    _append(path, "paging_check", {"rows_per_step": 10.1,
                                   "sustained_rows_ratio": 2.51})
    _append(path, "serving_bench", {"ttft_p99_ms": 195.0})
    assert perf_ledger.main(["check", "--ledger", path]) == 0
    capsys.readouterr()
    # 2a. Doctored rows/step drop > 10%: fails, metric named, both
    # rows printed.
    _append(path, "paging_check", {"rows_per_step": 8.0,
                                   "sustained_rows_ratio": 2.50})
    assert perf_ledger.main(["check", "--ledger", path]) == 1
    out = capsys.readouterr().out
    assert "FAIL paging_check: rows_per_step regressed" in out
    assert "direction=up" in out
    assert "current row:" in out and "baseline row:" in out
    assert out.count('"rows_per_step"') >= 2  # both rows printed
    perf_ledger.main(["accept", "--ledger", path, "--source",
                      "paging_check", "--note", "test baseline"])
    capsys.readouterr()
    # 2b. TTFT p99 inflated > 10%: fails direction-aware.
    _append(path, "serving_bench", {"ttft_p99_ms": 220.0})
    assert perf_ledger.main(["check", "--ledger", path]) == 1
    out = capsys.readouterr().out
    assert "FAIL serving_bench: ttft_p99_ms regressed" in out
    assert "direction=down" in out
    # 3. Only foreign-rig baselines: documented skip, rc 0.
    path2 = str(tmp_path / "L2.json")
    _append(path2, "paging_check", {"rows_per_step": 10.0}, rig=RIG_B)
    _append(path2, "paging_check", {"rows_per_step": 1.0}, rig=RIG_A)
    assert perf_ledger.main(["check", "--ledger", path2]) == 0
    out = capsys.readouterr().out
    assert "SKIP paging_check: no same-rig baseline" in out
    assert "documented skip" in out


def test_committed_ledger_validates_and_gates_clean():
    """The committed PERF_LEDGER.json must always be a state `make
    perf-check` accepts (pass or documented skip — never a standing
    failure, never a schema error)."""
    path = os.path.join(REPO_ROOT, "PERF_LEDGER.json")
    assert os.path.exists(path), "committed PERF_LEDGER.json missing"
    doc = perf_ledger.load_ledger(path)
    assert perf_ledger.validate_doc(doc) == []
    assert doc["rows"], "committed ledger has no seeded history"
    failures, _, out = _check(path)
    assert failures == [], out


def test_append_manifest_costs(tmp_path):
    path = str(tmp_path / "L.json")
    manifest = tmp_path / "MANIFEST.json"
    manifest.write_text(json.dumps({
        "platform": "cpu",
        "programs": {
            "engine.paged_step": {"cost": {"flops": 1000.0,
                                           "bytes_accessed": 4096.0}},
            "train.step": {"cost": {"flops": 2.0e6,
                                    "bytes_accessed": 1.0e6}},
        }}))
    rc = perf_ledger.main(["append-manifest", "--ledger", path,
                           "--manifest", str(manifest)])
    assert rc == 0
    (row,) = perf_ledger.load_ledger(path)["rows"]
    assert row["source"] == "program_manifest"
    assert row["metrics"]["flops:engine.paged_step"] == 1000.0
    assert row["metrics"]["bytes_accessed:train.step"] == 1.0e6
    # Program cost is a "down" metric: a 20% FLOPs rise regresses.
    manifest.write_text(json.dumps({
        "platform": "cpu",
        "programs": {
            "engine.paged_step": {"cost": {"flops": 1200.0,
                                           "bytes_accessed": 4096.0}},
            "train.step": {"cost": {"flops": 2.0e6,
                                    "bytes_accessed": 1.0e6}},
        }}))
    assert perf_ledger.main(["append-manifest", "--ledger", path,
                             "--manifest", str(manifest)]) == 0
    failures, _, out = _check(path)
    assert failures == ["program_manifest"]
    assert "flops:engine.paged_step regressed" in out


# ---------------------------------------------------------------------------
# Satellites: freshness, promotion, report, bench skip row
# ---------------------------------------------------------------------------


def test_ledger_freshness_gate(tmp_path):
    """artifact_freshness learns the ledger: fresh = measured +
    schema-valid + SAME rig + young. Everything else re-measures."""
    path = str(tmp_path / "L.json")
    _append(path, "serving_bench", {"ttft_p99_ms": 200.0})
    fresh = artifact_freshness.ledger_is_fresh
    assert fresh(path, "serving_bench", 1, RIG_A)
    # Foreign rig's recency says nothing about this rig.
    assert not fresh(path, "serving_bench", 1, RIG_B)
    # Unknown section.
    assert not fresh(path, "decode_bench", 1, RIG_A)
    # Too old.
    import time
    assert not fresh(path, "serving_bench", 1, RIG_A,
                     now=time.time() + 2 * 86400)
    # A skipped_unmeasurable row never grants freshness — the rig
    # still owes the section a run.
    path2 = str(tmp_path / "L2.json")
    _append(path2, "serving_bench", {}, status="skipped_unmeasurable",
            note="probe hung")
    assert not fresh(path2, "serving_bench", 1, RIG_A)
    # Unreadable/absent ledgers are stale, not crashes.
    assert not fresh(str(tmp_path / "absent.json"), "x", 1, RIG_A)
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    assert not fresh(str(bad), "x", 1, RIG_A)


def test_promote_serving_appends_ledger_row(tmp_path):
    """Satellite: the serving promotion lands its server_stats as a
    ledger row in the same transaction — and a refused promotion
    appends nothing."""
    raw = tmp_path / "raw.json"
    stats = tmp_path / "stats.json"
    out = tmp_path / "SERVING_BENCH.json"
    ledger = tmp_path / "L.json"
    ok_run = {"requests": 300, "errors": 0, "qps": 50.0,
              "p50_ms": 90.0, "p99_ms": 200.0}
    raw.write_text(json.dumps({"cold": ok_run, "warm": ok_run}))
    stats.write_text(json.dumps(
        {"platform": "tpu", "devices": ["TPU v5 lite0"],
         "batch_occupancy_avg": 5.21, "slots_active": 3,
         "slots_free": 5, "queue_depth": 2, "engine_steps": 4096,
         "rows_decoded": 21340, "ttft_p50_ms": 35.0,
         "ttft_p99_ms": 120.0, "tpot_p50_ms": 9.0,
         "tpot_p99_ms": 22.0, "hbm_peak_bytes": 123456,
         "prefix_hit_rate": 0.825, "kv_block_utilization": 0.7}))
    proc = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "promote_artifact.py"),
         "serving", str(raw), str(stats), str(out),
         "--ledger", str(ledger)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    (row,) = perf_ledger.load_ledger(str(ledger))["rows"]
    assert row["source"] == "serving_bench"
    assert row["fingerprint"]["platform"] == "tpu"
    assert row["metrics"]["ttft_p99_ms"] == 120.0
    assert row["metrics"]["batch_occupancy_avg"] == 5.21
    assert row["metrics"]["kv_block_utilization"] == 0.7
    assert row["metrics"]["qps"] == 50.0
    assert json.loads(out.read_text())["server_stats"]
    # Refused promotion (CPU platform): artifact untouched AND no row.
    stats.write_text(json.dumps({"platform": "cpu", "devices": []}))
    proc = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "promote_artifact.py"),
         "serving", str(raw), str(stats), str(out),
         "--ledger", str(ledger)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert len(perf_ledger.load_ledger(str(ledger))["rows"]) == 1


def test_perf_report_trend_and_annotations(tmp_path):
    path = str(tmp_path / "L.json")
    _append(path, "paging_check", {"rows_per_step": 10.0})
    _append(path, "paging_check", {"rows_per_step": 10.2})
    _append(path, "paging_check", {}, status="skipped_unmeasurable",
            note="window lost")
    _append(path, "paging_check", {"rows_per_step": 5.0})
    _append(path, "paging_check", {"rows_per_step": 20.0}, rig=RIG_B)
    report = perf_report.build_report(perf_ledger.load_ledger(path))
    rigs = report["sources"]["paging_check"]
    assert len(rigs) == 2  # cross-rig series never merge
    (label_a,) = [label for label, hist in rigs.items()
                  if hist["fingerprint"] == RIG_A]
    hist = rigs[label_a]
    assert [p["value"] for p in
            hist["series"]["rows_per_step"]] == [10.0, 10.2, 5.0]
    assert hist["rows"] == 3 and hist["skipped_rows"] == 1
    # The 10.2 -> 5.0 drop is annotated; last-known-good is the 10.2.
    regs = [a for a in hist["regressions"] if not a.get("skipped")]
    assert regs and regs[0]["metric"] == "rows_per_step"
    assert hist["last_known_good"]["metrics"]["rows_per_step"] == 10.2
    text = perf_report.format_report(report)
    assert "rows_per_step: 10.0 -> 10.2 -> 5.0" in text
    assert "regressed" in text
    # An invalid ledger is refused, not half-rendered.
    with pytest.raises(perf_ledger.LedgerError):
        perf_report.build_report({"schema_version": 99, "rows": []})


def test_bench_headline_wedged_rig_writes_skip_row(tmp_path):
    """Acceptance: on this CPU rig a full bench.py run finishes in
    seconds with a fingerprinted skip row in the ledger (instead of
    wedging through probe retries), and perf-check reads it as no
    data."""
    ledger = str(tmp_path / "L.json")
    env = dict(os.environ, BENCH_PERF_LEDGER=ledger,
               BENCH_PROBE_TIMEOUT_S="60", JAX_PLATFORMS="cpu")
    env.pop("BENCH_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        capture_output=True, text=True, timeout=240, env=env)
    assert proc.returncode == 1
    last = json.loads(proc.stdout.strip().splitlines()[-1])
    assert last["status"] == "skipped_unmeasurable"
    assert last["fingerprint"]["platform"] == "cpu"
    (row,) = perf_ledger.load_ledger(ledger)["rows"]
    assert row["source"] == "bench_headline"
    assert row["status"] == "skipped_unmeasurable"
    assert "cpu" in (row.get("note") or "")
    failures, skips, out = _check(ledger)
    assert failures == [] and skips == ["bench_headline"]
    assert "no data" in out
