# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Chip-backend tests: native/python parity against one synthetic tree.

Test shape follows the reference's fake-/dev and fake-/proc technique
(beta_plugin_test.go:34-61, mig/mig_test.go:28-128), applied at the
chip-library layer.
"""

import pytest

from container_engine_accelerators_tpu.chip import (
    BadShapeError,
    Health,
    NativeChipBackend,
    NonUniformPartitionError,
    NoSuchChipError,
    PyChipBackend,
)
from tests.conftest import NATIVE_LIB


def backends():
    out = [pytest.param(PyChipBackend, id="python")]
    if NATIVE_LIB:
        out.append(pytest.param(
            lambda: NativeChipBackend(NATIVE_LIB), id="native"))
    return out


@pytest.fixture(params=backends())
def backend(request):
    b = request.param()
    yield b
    b.shutdown()


def make_v5e8(node):
    for i in range(8):
        node.add_chip(i)
    node.set_topology("2x4")


def test_enumeration_and_topology(backend, fake_node):
    make_v5e8(fake_node)
    assert backend.init(fake_node.dev_dir, fake_node.state_dir) == 8
    assert backend.chip_count() == 8
    assert backend.topology() == (2, 4, 1)
    assert backend.chip_coords(5) == (1, 1, 0)
    assert backend.chip_at(1, 1, 0) == 5
    with pytest.raises(NoSuchChipError):
        backend.chip_coords(99)


def test_empty_dev_dir(backend, fake_node):
    assert backend.init(fake_node.dev_dir, fake_node.state_dir) == 0
    assert backend.chip_count() == 0


def test_non_accel_nodes_ignored(backend, fake_node):
    make_v5e8(fake_node)
    import os
    open(os.path.join(fake_node.dev_dir, "accelfoo"), "w").close()
    open(os.path.join(fake_node.dev_dir, "nvidia0"), "w").close()
    assert backend.init(fake_node.dev_dir, fake_node.state_dir) == 8


def test_subslice_tiling(backend, fake_node):
    make_v5e8(fake_node)
    backend.init(fake_node.dev_dir, fake_node.state_dir)
    assert backend.subslice_count("2x2") == 2
    assert backend.subslice_count("1x1") == 8
    assert backend.subslice_count("2x4") == 1
    assert backend.subslice_chips("2x2", 0) == [0, 1, 4, 5]
    assert backend.subslice_chips("2x2", 1) == [2, 3, 6, 7]


def test_subslice_uniformity_invariant(backend, fake_node):
    make_v5e8(fake_node)
    backend.init(fake_node.dev_dir, fake_node.state_dir)
    for bad in ("2x3", "3x1", "4x4"):
        with pytest.raises(NonUniformPartitionError):
            backend.subslice_count(bad)


def test_subslice_bad_shapes(backend, fake_node):
    make_v5e8(fake_node)
    backend.init(fake_node.dev_dir, fake_node.state_dir)
    for bad in ("", "x", "2x", "axb", "2x2x2x2", "0x2"):
        with pytest.raises(BadShapeError):
            backend.subslice_count(bad)


def test_health_states(backend, fake_node):
    make_v5e8(fake_node)
    backend.init(fake_node.dev_dir, fake_node.state_dir)
    assert backend.chip_health(0) == Health.OK
    fake_node.set_state(2, "health", "uncorrectable_ecc\n")
    assert backend.chip_health(2) == Health.UNCORRECTABLE_ECC
    fake_node.set_state(3, "health", "ici_link_down")
    assert backend.chip_health(3) == Health.ICI_LINK_DOWN
    fake_node.set_state(4, "health", "something-new")
    assert backend.chip_health(4) == Health.UNKNOWN
    fake_node.set_state(2, "health", "ok")
    assert backend.chip_health(2) == Health.OK


def test_hbm(backend, fake_node):
    make_v5e8(fake_node)
    backend.init(fake_node.dev_dir, fake_node.state_dir)
    assert backend.chip_hbm(0) is None
    fake_node.set_state(0, "hbm", "17179869184 1048576\n")
    assert backend.chip_hbm(0) == (17179869184, 1048576)


def test_duty_cycle_window_average(backend, fake_node):
    make_v5e8(fake_node)
    backend.init(fake_node.dev_dir, fake_node.state_dir)
    assert backend.duty_cycle(0, 10_000_000) is None
    assert backend.sample_duty(0) is False  # nothing published yet
    fake_node.set_state(0, "duty_cycle", "0 0")
    assert backend.sample_duty(0) is True
    fake_node.set_state(0, "duty_cycle", "600000 1000000")
    assert backend.sample_duty(0) is True
    assert backend.duty_cycle(0, 10_000_000) == pytest.approx(60.0)


def test_hotplug_rescan(backend, fake_node):
    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("1x2")
    assert backend.init(fake_node.dev_dir, fake_node.state_dir) == 2
    fake_node.add_chip(2)
    fake_node.add_chip(3)
    fake_node.set_topology("2x2")
    assert backend.rescan() == 4
    assert backend.topology() == (2, 2, 1)
    assert backend.chip_at(1, 1, 0) == 3


def test_explicit_coords_override(backend, fake_node):
    for i in range(4):
        fake_node.add_chip(i)
    fake_node.set_topology("2x2")
    # Swap chips 2 and 3 on the torus via published coords.
    fake_node.set_state(0, "coords", "0,0,0")
    fake_node.set_state(1, "coords", "0,1,0")
    fake_node.set_state(2, "coords", "1,1,0")
    fake_node.set_state(3, "coords", "1,0,0")
    backend.init(fake_node.dev_dir, fake_node.state_dir)
    assert backend.chip_at(1, 0, 0) == 3
    assert backend.chip_at(1, 1, 0) == 2
    assert backend.subslice_chips("1x2", 1) == [3, 2]
