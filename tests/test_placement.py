# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Placement subsystem tests: profile store, scorer, repartition
policy, and the gRPC INVALID_ARGUMENT contract.

The scorer/policy math is checked against hand-computed values on
small tori (the formulas in placement.py are simple enough to verify
by hand); the episode state machine is driven through forced
fragmentation exactly as tools/placement_check.py drives it, but at
the unit seam.
"""

import json

import grpc
import pytest

from container_engine_accelerators_tpu import obs
from container_engine_accelerators_tpu.chip import PyChipBackend
from container_engine_accelerators_tpu.plugin import api
from container_engine_accelerators_tpu.plugin import config as cfg
from container_engine_accelerators_tpu.plugin import placement
from container_engine_accelerators_tpu.plugin.manager import TpuManager
from tests.plugin_helpers import ServingManager, short_tmpdir


@pytest.fixture(autouse=True)
def _clean_tracer():
    obs.TRACER.reset()
    yield
    obs.TRACER.reset()


def make_manager(fake_node, topo="4x4", partition=""):
    dims = [int(d) for d in topo.split("x")]
    while len(dims) < 3:
        dims.append(1)
    n = dims[0] * dims[1] * dims[2]
    for i in range(n):
        fake_node.add_chip(i)
    fake_node.set_topology(topo)
    mgr = TpuManager(
        dev_dir=fake_node.dev_dir, state_dir=fake_node.state_dir,
        backend=PyChipBackend(),
        tpu_config=cfg.TpuConfig(tpu_partition_size=partition))
    mgr.start()
    return mgr


# -- profile store ----------------------------------------------------


def test_profile_store_ewma_and_demand():
    store = placement.ProfileStore(path="", alpha=0.5)
    assert store.demand("default/train") is None
    store.observe("default/train", mfu=0.8, hbm_frac=0.4)
    assert store.demand("default/train") == pytest.approx(0.8)
    store.observe("default/train", mfu=0.4)
    # EWMA: 0.5*0.8 + 0.5*0.4 = 0.6; hbm stays 0.4 -> max is mfu.
    assert store.demand("default/train") == pytest.approx(0.6)
    # Values clamp into [0, 1] (a junk telemetry sample must not
    # poison the profile).
    store.observe("default/clamp", mfu=7.0, hbm_frac=-3.0)
    assert store.demand("default/clamp") == pytest.approx(1.0)


def test_profile_store_effective_chips_advisory():
    store = placement.ProfileStore(path="")
    store.observe("default/embedder", mfu=0.2, weight=1.0)
    # MISO sizing: ceil(8 * 0.2) = 2, floor of 1.
    assert store.effective_chips("default/embedder", 8) == 2
    assert store.effective_chips("default/embedder", 1) == 1
    assert store.effective_chips("default/unknown", 8) is None


def test_profile_store_operator_seed_file(tmp_path):
    path = tmp_path / "profiles.json"
    path.write_text(json.dumps(
        {"default/trainer": {"mfu": 0.9, "hbm_frac": 0.7},
         "default/embedder": {"mfu": 0.1},
         "junk": "not-a-dict"}))
    store = placement.ProfileStore(path=str(path))
    assert len(store) == 2
    assert store.demand("default/trainer") == pytest.approx(0.9)
    # A malformed file warns and loads nothing (bad mounts must not
    # kill the plugin).
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    assert placement.ProfileStore(path=str(bad)).demand("x") is None


# -- scorer -----------------------------------------------------------


def test_scorer_terms_hand_computed():
    """4x4 free grid, size-4 candidates: an edge 1x4 row costs less
    largest-box than a center 2x2, exactly as hand-computed."""
    dims = (4, 4, 1)
    free = [(x, y, 0) for x in range(4) for y in range(4)]
    scorer = placement.PlacementScorer(
        w_compact=1.0, w_frag=1.0, w_profile=1.0, enabled=True)
    grid = placement.CoordGrid(free, dims)
    row = [(0, y, 0) for y in range(4)]        # edge row
    center = [(x, y, 0) for x in (1, 2) for y in (1, 2)]
    # row: compact 0; largest box 16 -> 12 (3x4): frag (16-12)/4 = 1.
    assert scorer.score(row, grid, dims, 4) == pytest.approx(1.0)
    # center 2x2: compact 0; it blocks both middle rows AND columns,
    # so 16 -> 4 (edge rows/cols only): frag (16-4)/4 = 3.
    assert scorer.score(center, grid, dims, 4) == pytest.approx(3.0)
    # Profile fit: heavy demand (1.0) weights compactness (0 for a
    # box), light demand (0.0) doubles the fragmentation penalty.
    assert scorer.score(center, grid, dims, 4, demand=0.0) == \
        pytest.approx(6.0)
    assert scorer.score(center, grid, dims, 4, demand=1.0) == \
        pytest.approx(3.0)


def test_scorer_choose_deterministic_tie_break():
    dims = (2, 2, 1)
    free = [(0, 0, 0), (0, 1, 0), (1, 0, 0), (1, 1, 0)]
    scorer = placement.PlacementScorer(enabled=True)
    cands = [(["accel2", "accel3"], [(1, 0, 0), (1, 1, 0)]),
             (["accel0", "accel1"], [(0, 0, 0), (0, 1, 0)])]
    ids, score = scorer.choose(cands, free, dims, 2)
    assert ids == ["accel0", "accel1"]   # natural-least wins the tie
    ids2, _ = scorer.choose(list(reversed(cands)), free, dims, 2)
    assert ids2 == ids


def test_largest_box_volume():
    dims = (4, 4, 1)
    coords = [(x, y, 0) for x in range(4) for y in range(4)
              if (x, y) != (1, 1)]
    assert placement.largest_box_volume(coords, dims) == 8
    assert placement.largest_box_volume([], dims) == 0


def test_profile_fit_changes_the_choice(fake_node, monkeypatch):
    """A measured-light workload gets the scatter that preserves the
    big box when the box candidates are more destructive — the
    MISO behavior, end to end through preferred_allocation."""
    mgr = make_manager(fake_node, "4x4")
    hint = fake_node.dev_dir + "/hint"
    with open(hint, "w") as f:
        f.write("default/embedder")
    monkeypatch.setenv(placement.HINT_FILE_ENV, hint)
    profiles = mgr.placement_profiles()
    profiles.observe("default/embedder", mfu=0.05, weight=1.0)
    available = [f"accel{i}" for i in range(16)]
    light = mgr.preferred_allocation(available, [], 2)
    # The decision is journaled with workload + advisory sizing.
    events = [e for e in obs.TRACER.snapshot()["events"]
              if e["name"] == placement.DECISION_EVENT]
    assert events, "no placement.decision event"
    assert events[-1]["fields"]["workload"] == "default/embedder"
    assert events[-1]["fields"]["effective_chips"] == 1
    assert len(light) == 2


# -- repartition policy -----------------------------------------------


def live_slices(*ids):
    return set(ids)


def test_policy_episode_hysteresis_and_drain_gate(fake_node):
    mgr = make_manager(fake_node, "4x4", partition="4x1")
    mgr.allocate_envs(["tpu-4x1-0"])
    mgr.allocate_envs(["tpu-4x1-2"])
    live = {"tpu-4x1-0", "tpu-4x1-2"}
    policy = placement.RepartitionPolicy(mgr, threshold=0.5)

    # Liveness unknown: the pass is skipped entirely.
    assert policy.evaluate(live_device_ids=None) is None
    assert not obs.TRACER.gauges()

    for _ in range(3):
        result = policy.evaluate(live_device_ids=live)
    assert result["fragmentation"] == pytest.approx(0.5)
    assert policy.proposal_count() == 1           # one per episode
    assert policy.pending_proposal() == "2x2"

    # Drain gate: live or unknown liveness never applies.
    assert policy.maybe_apply(live) is None
    assert policy.maybe_apply(None) is None
    assert mgr.partition_shape() == "4x1"

    # Recovery (the allocations drain): fragmentation falls to 0,
    # the episode closes once, and the pending proposal SURVIVES —
    # the tiling/demand mismatch it fixes is still there.
    policy.evaluate(live_device_ids=set())
    assert policy.pending_proposal() == "2x2"
    names = [e["name"] for e in obs.TRACER.snapshot()["events"]]
    assert names.count(placement.PROPOSED_EVENT) == 1
    assert names.count(placement.RECOVERED_EVENT) == 1

    assert policy.maybe_apply(set()) == "2x2"
    assert mgr.partition_shape() == "2x2"
    assert sorted(mgr.list_devices()) == [
        "tpu-2x2-0", "tpu-2x2-1", "tpu-2x2-2", "tpu-2x2-3"]
    assert names.count(placement.APPLIED_EVENT) == 0  # pre-apply snap
    names = [e["name"] for e in obs.TRACER.snapshot()["events"]]
    assert names.count(placement.APPLIED_EVENT) == 1
    # Applying clears the pending proposal; a fresh drained pass
    # proposes nothing new (demand now matches the tiling).
    assert policy.maybe_apply(set()) is None


def test_policy_gauges_ride_the_stale_label_reset(fake_node):
    """The placement gauges participate in the metrics stale-label
    reset cycle: series under a superseded shape label drop, the
    live shape's series survive (the policy re-publishes on its own
    cadence; dropping the live series would blink them off the
    scrape between passes)."""
    from container_engine_accelerators_tpu.plugin.metrics import (
        MetricServer,
    )

    mgr = make_manager(fake_node, "4x4", partition="4x1")
    policy = placement.RepartitionPolicy(mgr, threshold=0.5)
    policy.evaluate(live_device_ids=set())
    gauges = obs.get_tracer().gauges()
    assert any(k[0] == placement.FRAGMENTATION_GAUGE
               and ("shape", "4x1") in k[1] for k in gauges)

    # A repartition supersedes the 4x1 series; the next reset sheds
    # them while the 2x2 series (published post-repartition) stays.
    mgr.repartition("2x2")
    policy.evaluate(live_device_ids=set())
    server = MetricServer(mgr, mgr._backend, port=0)
    server._reset()
    gauges = obs.get_tracer().gauges()
    assert not any(("shape", "4x1") in k[1] for k in gauges
                   if k[0] in placement.PLACEMENT_GAUGES)
    assert any(k[0] == placement.FRAGMENTATION_GAUGE
               and ("shape", "2x2") in k[1] for k in gauges)


def test_policy_propose_needs_journal_demand(fake_node):
    """No allocate.decision history -> nothing to size a re-tiling
    for -> no proposal even over the fragmentation threshold."""
    mgr = make_manager(fake_node, "4x4", partition="4x1")
    obs.TRACER.reset()   # drop the allocate-free startup journal
    policy = placement.RepartitionPolicy(mgr, threshold=0.1)
    result = policy.evaluate(
        live_device_ids={"tpu-4x1-0", "tpu-4x1-2"})
    assert result["fragmentation"] > 0.1
    assert policy.proposal_count() == 0
    assert policy.pending_proposal() is None


def test_policy_proposes_with_tracing_disabled(fake_node):
    """CEA_TPU_TRACE=0 records no allocate.decision events; the
    policy must fall back to the manager's tracer-independent demand
    counter instead of going silently inert (the PR-5 efficiency-
    ledger bare-path discipline)."""
    mgr = make_manager(fake_node, "4x4", partition="4x1")
    obs.TRACER.enabled = False
    try:
        mgr.allocate_envs(["tpu-4x1-0"])
        mgr.allocate_envs(["tpu-4x1-2"])
        assert mgr.demand_histogram() == {4: 2}
        policy = placement.RepartitionPolicy(mgr, threshold=0.5)
        result = policy.evaluate(
            live_device_ids={"tpu-4x1-0", "tpu-4x1-2"})
        assert result["fragmentation"] == pytest.approx(0.5)
        assert policy.pending_proposal() == "2x2"
    finally:
        obs.TRACER.enabled = True


def test_failed_apply_reopens_the_episode(fake_node):
    """A re-tile that fails for a non-drain reason (topology changed
    under the proposal) drops the proposal AND closes the episode: a
    still-fragmented node must re-propose at the next pass, not wedge
    with episode=True and nothing pending."""
    mgr = make_manager(fake_node, "4x4", partition="4x1")
    mgr.allocate_envs(["tpu-4x1-0"])
    mgr.allocate_envs(["tpu-4x1-2"])
    live = {"tpu-4x1-0", "tpu-4x1-2"}
    policy = placement.RepartitionPolicy(mgr, threshold=0.5)
    policy.evaluate(live_device_ids=live)
    assert policy.pending_proposal() == "2x2"

    orig = mgr.repartition

    def boom(*a, **k):
        raise RuntimeError("topology changed")

    mgr.repartition = boom
    assert policy.maybe_apply(set()) is None
    assert policy.pending_proposal() is None
    mgr.repartition = orig

    # The node is still fragmented: the next pass opens a fresh
    # episode and proposes again.
    policy.evaluate(live_device_ids=live)
    assert policy.pending_proposal() == "2x2"
    assert policy.proposal_count() == 2
    assert policy.maybe_apply(set()) == "2x2"
    assert mgr.partition_shape() == "2x2"


def test_drain_race_defers_and_keeps_the_proposal(fake_node):
    """An Allocate landing between the drained-liveness snapshot and
    the apply must NOT be re-tiled out from under: the epoch guard
    defers the apply and the proposal survives for the next pass."""
    mgr = make_manager(fake_node, "4x4", partition="4x1")
    mgr.allocate_envs(["tpu-4x1-0"])
    mgr.allocate_envs(["tpu-4x1-2"])
    policy = placement.RepartitionPolicy(mgr, threshold=0.5)
    policy.evaluate(live_device_ids={"tpu-4x1-0", "tpu-4x1-2"})
    assert policy.pending_proposal() == "2x2"

    epoch = policy.manager_epoch()
    # ... liveness snapshot says drained, then a pod sneaks in:
    mgr.allocate_envs(["tpu-4x1-1"])
    assert policy.maybe_apply(set(), epoch=epoch) is None
    assert mgr.partition_shape() == "4x1"          # no re-tile
    assert policy.pending_proposal() == "2x2"      # proposal kept
    # A fresh (genuinely drained) pass applies.
    assert policy.maybe_apply(set(),
                              epoch=policy.manager_epoch()) == "2x2"
    assert mgr.partition_shape() == "2x2"


def test_applied_repartition_survives_plugin_restart(fake_node):
    """The config file (usually a read-only hostPath) still says the
    old size after a policy re-tiling; a restarted plugin must resume
    the applied tiling, not silently revert — unless the operator
    changed the configured size, which wins."""
    mgr = make_manager(fake_node, "4x4", partition="4x1")
    mgr.repartition("2x2")

    restarted = TpuManager(
        dev_dir=fake_node.dev_dir, state_dir=fake_node.state_dir,
        backend=PyChipBackend(),
        tpu_config=cfg.TpuConfig(tpu_partition_size="4x1"))
    restarted.start()
    assert restarted.partition_shape() == "2x2"
    assert sorted(restarted.list_devices()) == [
        "tpu-2x2-0", "tpu-2x2-1", "tpu-2x2-2", "tpu-2x2-3"]

    # Operator reconfigure invalidates the stored re-tiling.
    reconfigured = TpuManager(
        dev_dir=fake_node.dev_dir, state_dir=fake_node.state_dir,
        backend=PyChipBackend(),
        tpu_config=cfg.TpuConfig(tpu_partition_size="1x4"))
    reconfigured.start()
    assert reconfigured.partition_shape() == "1x4"


def test_repartition_refuses_unpartitioned_node(fake_node):
    mgr = make_manager(fake_node, "2x2")
    with pytest.raises(ValueError, match="not partitioned"):
        mgr.repartition("1x2")


def test_placement_loop_once_applies_when_drained(fake_node):
    mgr = make_manager(fake_node, "4x4", partition="4x1")
    mgr.allocate_envs(["tpu-4x1-0"])
    mgr.allocate_envs(["tpu-4x1-2"])
    live = [{"tpu-4x1-0", "tpu-4x1-2"}, set()]
    policy = placement.RepartitionPolicy(mgr, threshold=0.5)
    loop = placement.PlacementLoop(policy, lambda: live[0],
                                   interval_s=3600)
    assert loop.loop_once() is None          # fragmented but live
    live[0] = set()
    assert loop.loop_once() == "2x2"         # drained -> applied
    assert mgr.partition_shape() == "2x2"


# -- gRPC contract ----------------------------------------------------


def test_oversize_preference_is_invalid_argument_over_grpc(fake_node):
    mgr = make_manager(fake_node, "2x2")
    plugin_dir = short_tmpdir()
    with ServingManager(mgr, plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1Beta1Stub(ch)
            with pytest.raises(grpc.RpcError) as exc:
                stub.GetPreferredAllocation(
                    api.v1beta1_pb2.PreferredAllocationRequest(
                        container_requests=[
                            api.v1beta1_pb2
                            .ContainerPreferredAllocationRequest(
                                available_deviceIDs=["accel0",
                                                     "accel1"],
                                allocation_size=5)]), timeout=10)
            assert exc.value.code() == \
                grpc.StatusCode.INVALID_ARGUMENT
            # A satisfiable request on the same stream still works.
            resp = stub.GetPreferredAllocation(
                api.v1beta1_pb2.PreferredAllocationRequest(
                    container_requests=[
                        api.v1beta1_pb2
                        .ContainerPreferredAllocationRequest(
                            available_deviceIDs=[
                                "accel0", "accel1", "accel2",
                                "accel3"],
                            allocation_size=2)]), timeout=10)
            assert list(resp.container_responses[0].deviceIDs) == \
                ["accel0", "accel1"]


def test_allocate_decision_carries_preference_score(fake_node):
    """The preferred_allocation -> Allocate handoff: the journal's
    allocate.decision for a set the kubelet just asked a preference
    for carries that preference's score."""
    mgr = make_manager(fake_node, "4x4")
    available = [f"accel{i}" for i in range(16)]
    chosen = mgr.preferred_allocation(available, [], 4)
    mgr.allocate_envs(chosen)
    decisions = [e for e in obs.TRACER.snapshot()["events"]
                 if e["name"] == "allocate.decision"]
    assert decisions
    assert isinstance(decisions[-1]["fields"].get("score"),
                      (int, float))
    # An Allocate that never went through a preference has no score.
    mgr.allocate_envs(["accel15"])
    last = [e for e in obs.TRACER.snapshot()["events"]
            if e["name"] == "allocate.decision"][-1]
    assert "score" not in last["fields"]
