# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Per-request latency attribution on the REAL engine service.

Drives _EngineService + SlotDecodeEngine directly (no HTTP; the
serving loop's HTTP tests live in test_serving.py) and pins the
reqledger contracts on real traffic: buckets sum to wall within 1%,
injected KV-block starvation comes back attributed to block_wait
(not smeared into queue_wait), cancel-mid-stream retires a balanced
record, /debug/requests has its documented shape and ring bound, and
reset_counters zeroes every piece of attribution/saturation state —
all while greedy streams stay token-identical to per-request
decode() (the instrumentation is host clocks only).
"""

import queue
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import TransformerLM
from container_engine_accelerators_tpu.models.decode import (
    SlotDecodeEngine,
    decode,
)
from container_engine_accelerators_tpu.serving.server import (
    _Admission,
    _EngineService,
    _EngineWork,
)

# The retired records round to microseconds; a sub-ms request's
# rounding residue must not read as a sum-to-wall violation.
SUM_TOL_ABS = 2e-5


@pytest.fixture(scope="module")
def lm():
    model = TransformerLM(vocab_size=48, embed_dim=32, num_layers=2,
                          num_heads=4, max_seq_len=32,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def service(lm):
    """One warmed paged-engine service shared by the non-starved
    tests (each compiles nothing beyond the module's first use)."""
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                           paged=True, kv_block_size=4, buckets=[8],
                           kv_quant="bf16", kv_spill=False)
    svc = _EngineService(eng, _Admission(0))
    yield svc
    svc.stop()


def _work(prompt, p_len, new, **kw):
    row = np.zeros((8,), np.int32)
    row[:p_len] = prompt[:p_len]
    return _EngineWork(row, p_len, new, 0.0, 0, 1.0, 0.0, 1.0, -1,
                       False, 0, None, **kw)


def _run(svc, works, timeout=300):
    assert svc.submit_many(works) is not None
    for w in works:
        status, out = w.done.get(timeout=timeout)
        assert status == "ok", out


def _assert_balanced(record):
    total = sum(record["buckets"].values())
    assert abs(total - record["wall_s"]) <= max(
        0.01 * record["wall_s"], SUM_TOL_ABS), record


def test_attribution_sums_to_wall_on_real_traffic(lm, service):
    """Real engine traffic: every retired record is a partition of
    its wall time, TTFT is inside the wall, and the greedy streams
    are untouched by the instrumentation."""
    model, params = lm
    service.reset_counters()
    prompts = [np.array([1, 2, 3, 4], np.int32),
               np.array([9, 8, 7, 6, 5, 4], np.int32),
               np.array([11, 12], np.int32)]
    news = [5, 4, 6]
    works = [_work(p, len(p), n) for p, n in zip(prompts, news)]
    _run(service, works)

    # Exactness oracle: per-request decode() at the widest horizon.
    width = max(len(p) for p in prompts)
    padded = np.zeros((len(prompts), width), np.int32)
    p_lens = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        padded[i, :len(p)] = p
        p_lens[i] = len(p)
    ref = np.asarray(decode(model, params, jnp.asarray(padded),
                            max(news), prompt_len=p_lens,
                            fast_prefill=False))
    for i, (w, p, n) in enumerate(zip(works, prompts, news)):
        assert w.tokens == ref[i, len(p):len(p) + n].tolist()

    records = service.debug_requests()["records"]
    assert len(records) == 3
    for rec in records:
        _assert_balanced(rec)
        assert rec["outcome"] == "completed"
        assert rec["ttft_s"] is not None
        assert rec["ttft_s"] <= rec["wall_s"] + 1e-6
    by_tokens = sorted(r["tokens"] for r in records)
    assert by_tokens == sorted(news)

    stats = service.stats()
    attribution = stats["latency_attribution"]
    assert attribution["prefill"]["count"] == 3
    assert attribution["prefill"]["total_s"] > 0
    sat = stats["saturation"]
    assert 0.0 <= sat["max"] <= 1.0
    assert "kv_blocks" in sat["causes"]  # the paged pool's cause


def test_block_starvation_attributes_block_wait(lm):
    """Injected starvation: an arena holding ONE worst-case row
    under three free slots serializes admissions — the queued
    requests' waits must land in block_wait (the engine names
    kv_blocks, not slots), and the saturation plane must read it."""
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=3, slot_len=16,
                           paged=True, kv_block_size=4, kv_blocks=5,
                           buckets=[8], kv_quant="bf16",
                           kv_spill=False)
    assert eng.admission_block_cause(
        np.arange(1, 5, dtype=np.int32), 4) is None
    svc = _EngineService(eng, _Admission(0))
    try:
        # No max_new bound -> each row reserves the worst case
        # (slot_len), which IS the whole arena: strict serialization.
        works = [_work(np.arange(1, 5, dtype=np.int32) + i, 4, 12)
                 for i in range(3)]
        _run(svc, works)
        records = svc.debug_requests()["records"]
        assert len(records) == 3
        for rec in records:
            _assert_balanced(rec)
        # Newest-first: the LAST retired request waited through both
        # predecessors' full runs — block-starved, not slot-starved.
        starved = records[0]
        assert starved["buckets"]["block_wait"] > 0
        assert (starved["buckets"]["block_wait"]
                > starved["buckets"]["queue_wait"])
        assert (starved["buckets"]["block_wait"]
                > starved["buckets"]["prefill"])
        sat = svc.stats()["saturation"]
        # The arena stayed fully reserved through the drain.
        assert sat["causes"]["kv_blocks"] >= 0.0
        assert svc.stats()["admission_blocked_on"] in (
            None, "kv_blocks")
    finally:
        svc.stop()


def test_engine_names_the_starved_resource(lm):
    """admission_block_cause: slots when the pool is full, kv_blocks
    when slots are free but the arena cannot reserve the span."""
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=1, slot_len=16,
                           paged=True, kv_block_size=4, buckets=[8],
                           kv_quant="bf16", kv_spill=False)
    row = np.arange(1, 5, dtype=np.int32)
    eng.admit(row, 4, max_new=2)
    assert eng.admission_block_cause(row, 4, 2) == "slots"
    avail, usable = eng.block_availability()
    assert 0 <= avail <= usable
    eng2 = SlotDecodeEngine(model, params, slots=3, slot_len=16,
                            paged=True, kv_block_size=4, kv_blocks=5,
                            buckets=[8], kv_quant="bf16",
                            kv_spill=False)
    eng2.admit(row, 4)  # worst-case reservation takes the arena
    assert eng2.admission_block_cause(row, 4) == "kv_blocks"
    assert not eng2.can_admit(row, 4)
    # Dense pool: no block cause, no availability surface.
    eng3 = SlotDecodeEngine(model, params, slots=1, slot_len=16,
                            paged=False)
    assert eng3.block_availability() is None
    assert eng3.admission_block_cause(row, 4) is None


def test_cancel_mid_stream_retires_balanced_record(lm, service):
    """A stream cancelled mid-flight still retires a record whose
    buckets partition its wall (the residue lands in `other`), with
    outcome `cancelled` and the stream flag set."""
    service.reset_counters()
    stream_q = queue.Queue()
    prompt = np.array([3, 1, 4, 1], np.int32)
    work = _EngineWork(
        np.concatenate([prompt, np.zeros((4,), np.int32)]), 4, 12,
        0.0, 0, 1.0, 0.0, 1.0, -1, False, 0, None, stream_q=stream_q)
    assert service.submit_many([work]) is not None
    got = 0
    while got < 2:
        item = stream_q.get(timeout=120)
        assert item[0] == "tok", item
        got += 1
    work.cancel.set()
    # Drain to the terminal item the retire pushes.
    deadline = time.monotonic() + 120
    while True:
        item = stream_q.get(timeout=max(1, deadline - time.monotonic()))
        if item[0] != "tok":
            break
    # Cancels are permanent: the envelope's retryable flag is False.
    assert item == ("error", "cancelled", False)
    rec = service.debug_requests()["records"][0]
    assert rec["outcome"] == "cancelled"
    assert rec["stream"] is True
    assert rec["tokens"] >= 2
    _assert_balanced(rec)


def test_debug_requests_shape_and_ring_bound(lm, monkeypatch):
    """The documented /debug/requests payload shape, the ?n= cap,
    and the CEA_TPU_REQ_LEDGER_CAP ring bound."""
    monkeypatch.setenv("CEA_TPU_REQ_LEDGER_CAP", "2")
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                           paged=True, kv_block_size=4, buckets=[8],
                           kv_quant="bf16", kv_spill=False)
    svc = _EngineService(eng, _Admission(0))
    try:
        works = [_work(np.arange(1, 5, dtype=np.int32) + i, 4, 2)
                 for i in range(3)]
        _run(svc, works)
        payload = svc.debug_requests()
        assert payload["capacity"] == 2
        assert payload["retired_total"] == 3
        assert len(payload["records"]) == 2  # the ring bound
        assert set(payload["latency_attribution"]) >= {
            "queue_wait", "block_wait", "prefill", "rehydrate",
            "decode_gap", "stream_backpressure", "other"}
        for rec in payload["records"]:
            assert {"submit_unix", "wall_s", "buckets", "outcome",
                    "tokens", "stream", "ttft_s",
                    "prompt_len"} <= set(rec)
        assert len(svc.debug_requests(limit=1)["records"]) == 1
    finally:
        svc.stop()


def test_reset_counters_zeroes_attribution_and_saturation(lm,
                                                          service):
    """The PR 11 bug class, pinned: reset_counters must zero the
    attribution ring, the per-bucket histograms, and the saturation
    snapshot alongside the engine counters."""
    _run(service, [_work(np.array([7, 7, 2, 9], np.int32), 4, 3)])
    assert service.debug_requests()["retired_total"] >= 1
    service.reset_counters()
    payload = service.debug_requests()
    assert payload["retired_total"] == 0
    assert payload["records"] == []
    stats = service.stats()
    assert all(v["count"] == 0 and v["total_s"] == 0.0
               for v in stats["latency_attribution"].values())
    assert stats["admission_blocked_on"] is None
    # The snapshot dropped with the reset; stats falls back to a
    # freshly computed slots-only view until the loop republishes.
    assert 0.0 <= stats["saturation"]["max"] <= 1.0
