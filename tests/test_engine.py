# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Continuous-batching slot engine (models.decode.SlotDecodeEngine).

The engine's correctness contract is EXACTNESS against the
per-request decode paths: a slot's greedy token stream — admitted
mid-flight into a pool whose other slots are at arbitrary positions —
must be token-for-token what ``decode`` produces for that request
alone. These tests drive the engine directly (no HTTP; the serving
loop's tests live in test_serving.py) on models small enough for
tier-1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import TransformerLM
from container_engine_accelerators_tpu.models.decode import (
    SlotDecodeEngine,
    decode,
    greedy_decode,
)


def _make_lm(**kw):
    kwargs = dict(vocab_size=48, embed_dim=32, num_layers=2,
                  num_heads=4, max_seq_len=32, dtype=jnp.float32)
    kwargs.update(kw)
    model = TransformerLM(**kwargs)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def lm():
    return _make_lm()


def _drain(engine, slot, n):
    out = []
    for _ in range(n):
        toks, _ = engine.step()
        out.append(int(toks[slot]))
    return out


def test_staggered_admission_matches_greedy_decode(lm):
    """Two requests admitted TWO STEPS APART — the in-flight
    admission no batch decode can do — each emit exactly their
    per-request decode() stream; a ragged (right-padded) row matches
    the prompt_len-vector reference."""
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=3, slot_len=14)

    prompt_a = np.array([1, 2, 3, 4], np.int32)          # full width
    slot_a, first_a, _, _ = eng.admit(prompt_a, 4)
    out_a = [first_a] + _drain(eng, slot_a, 2)

    prompt_b = np.array([7, 9, 0, 0], np.int32)          # true len 2
    slot_b, first_b, _, _ = eng.admit(prompt_b, 2)
    out_b = [first_b]
    for _ in range(3):
        toks, _ = eng.step()
        out_a.append(int(toks[slot_a]))
        out_b.append(int(toks[slot_b]))
    eng.release(slot_a)
    out_b += _drain(eng, slot_b, 2)
    eng.release(slot_b)

    ref_a = np.asarray(greedy_decode(
        model, params, jnp.asarray(prompt_a[None]), 6))[0]
    assert out_a == ref_a[4:10].tolist()
    ref_b = np.asarray(decode(
        model, params, jnp.asarray(prompt_b[None]), 6,
        prompt_len=np.array([2]), fast_prefill=False))[0]
    assert out_b == ref_b[2:8].tolist()
    # Occupancy accounting saw the overlap: 3 of the 7 steps ran 2
    # rows.
    assert eng.steps == 7 and eng.row_steps == 10


def test_freed_slot_reused_immediately(lm):
    """EOS-style early retirement: releasing a finished slot makes it
    admissible on the SAME boundary, and the new occupant's stream is
    exact — the recycled cache row carries no trace of its previous
    occupant."""
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=1, slot_len=14)

    prompt_a = np.array([1, 2, 3, 4], np.int32)
    slot_a, first_a, _, _ = eng.admit(prompt_a, 4)
    _drain(eng, slot_a, 2)          # A "hits EOS" after 3 tokens
    eng.release(slot_a)
    assert eng.free_slots() == 1

    prompt_b = np.array([5, 6, 7, 8], np.int32)
    slot_b, first_b, _, _ = eng.admit(prompt_b, 4)
    assert slot_b == slot_a         # the recycled slot
    out_b = [first_b] + _drain(eng, slot_b, 5)
    eng.release(slot_b)
    ref_b = np.asarray(greedy_decode(
        model, params, jnp.asarray(prompt_b[None]), 6))[0]
    assert out_b == ref_b[4:10].tolist()


def test_mixed_sampling_pool_keeps_greedy_rows_exact(lm):
    """One step program serves any knob mix: a greedy row co-resident
    with a filtered-sampling row still emits its exact reference
    stream, and the sampled row stays in-vocab."""
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=14)
    slot_g, tok_g, _, _ = eng.admit(np.array([1, 2, 3, 4], np.int32), 4)
    slot_s, tok_s, _, _ = eng.admit(
        np.array([5, 6, 7, 8], np.int32), 4, temperature=0.9,
        top_k=5, top_p=0.9, min_p=0.02, seed=7)
    out_g, out_s = [tok_g], [tok_s]
    for _ in range(5):
        toks, _ = eng.step()
        out_g.append(int(toks[slot_g]))
        out_s.append(int(toks[slot_s]))
    ref_g = np.asarray(greedy_decode(
        model, params, jnp.asarray([[1, 2, 3, 4]], jnp.int32), 6))[0]
    assert out_g == ref_g[4:10].tolist()
    assert all(0 <= t < model.vocab_size for t in out_s)


def test_repetition_penalty_and_logprobs_match_decode(lm):
    """Per-slot penalty state (the seen-token mask survives across
    steps) and the logprob stream both match decode()'s reference."""
    model, params = lm
    prompt = np.array([3, 9, 3, 0], np.int32)
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=14)
    slot, tok0, _, _ = eng.admit(prompt, 3, repetition_penalty=2.5)
    out = [tok0] + _drain(eng, slot, 5)
    ref = np.asarray(decode(
        model, params, jnp.asarray(prompt[None]), 6,
        prompt_len=np.array([3]), fast_prefill=False,
        repetition_penalty=2.5))[0]
    assert out == ref[3:9].tolist()
    eng.release(slot)

    _, lps_ref = decode(
        model, params, jnp.asarray(prompt[None]), 6,
        prompt_len=np.array([3]), fast_prefill=False,
        return_logprobs=True)
    slot, tok0, lp0, echo = eng.admit(prompt, 3)
    lps = list(echo[:3]) + [lp0]
    for _ in range(5):
        toks, lp = eng.step()
        lps.append(float(lp[slot]))
    np.testing.assert_allclose(np.asarray(lps),
                               np.asarray(lps_ref)[0][:9], atol=1e-4)


def test_engine_rejects_unsupported_configs():
    model, params = _make_lm()
    with pytest.raises(ValueError, match="max_seq_len"):
        SlotDecodeEngine(model, params, slots=2, slot_len=64)
    # Windowed TARGETS run in slots now; a windowed DRAFT does not
    # (its cache would have to be full-length anyway), and a draft
    # model needs a chunk width.
    wmodel, wparams = _make_lm(attention_window=8)
    SlotDecodeEngine(wmodel, wparams, slots=2, slot_len=14)
    with pytest.raises(ValueError, match="dense cache"):
        SlotDecodeEngine(model, params, slots=2, slot_len=14,
                         draft_model=wmodel, draft_params=wparams,
                         spec_k=3)
    with pytest.raises(ValueError, match="spec_k"):
        SlotDecodeEngine(model, params, slots=2, slot_len=14,
                         draft_model=model, draft_params=params,
                         spec_k=1)


def test_admit_requires_free_slot(lm):
    model, params = lm
    eng = SlotDecodeEngine(model, params, slots=1, slot_len=14)
    eng.admit(np.array([1, 2], np.int32), 2)
    with pytest.raises(RuntimeError, match="free slot"):
        eng.admit(np.array([3, 4], np.int32), 2)


def test_windowed_staggered_admission_matches_decode():
    """Ring-cache (sliding-window) models run in slots: two windowed
    requests admitted mid-flight — prompts LONGER than the window,
    so the per-row band lower bound is live — each emit exactly
    their per-request decode() stream."""
    model, params = _make_lm(attention_window=8)
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=20)

    prompt_a = np.arange(1, 11, dtype=np.int32)      # 10 > window 8
    slot_a, first_a, _, _ = eng.admit(prompt_a, 10)
    out_a = [first_a] + _drain(eng, slot_a, 2)

    prompt_b = np.array([7, 9, 4, 2, 8, 6, 1, 3, 5, 0], np.int32)
    slot_b, first_b, _, _ = eng.admit(prompt_b, 9)   # ragged row
    out_b = [first_b]
    for _ in range(3):
        toks, _ = eng.step()
        out_a.append(int(toks[slot_a]))
        out_b.append(int(toks[slot_b]))
    eng.release(slot_a)
    out_b += _drain(eng, slot_b, 2)
    eng.release(slot_b)

    ref_a = np.asarray(decode(
        model, params, jnp.asarray(prompt_a[None]), 6,
        prompt_len=np.array([10]), fast_prefill=False))[0]
    assert out_a == ref_a[10:16].tolist()
    ref_b = np.asarray(decode(
        model, params, jnp.asarray(prompt_b[None]), 6,
        prompt_len=np.array([9]), fast_prefill=False))[0]
    assert out_b == ref_b[9:15].tolist()


def _drain_spec(eng, want):
    """Step a draft-configured engine until every tracked slot has
    its requested token count; surplus accepted tokens in a row's
    final chunk are discarded exactly as the serving loop discards
    them. ``want`` maps slot -> (list to fill, target length)."""
    pending = dict(want)
    while pending:
        toks, _, counts = eng.step()
        for slot, (out, n) in list(pending.items()):
            for j in range(int(counts[slot])):
                out.append(int(toks[slot, j]))
                if len(out) >= n:
                    del pending[slot]
                    break


def test_spec_engine_matches_speculative_decode_on_reused_slot():
    """Speculative decoding inside the slot engine: a greedy stream
    through a self-draft engine is token-identical to the module's
    ``speculative_decode`` (itself greedy-exact), and a SECOND
    request admitted into the recycled slot — draft arena included —
    is too, with acceptance telemetry moving."""
    from container_engine_accelerators_tpu.models.speculative import (
        speculative_decode,
    )

    model, params = _make_lm()
    eng = SlotDecodeEngine(model, params, slots=1, slot_len=14,
                           draft_model=model, draft_params=params,
                           spec_k=3)
    for prompt in (np.array([1, 2, 3, 4], np.int32),
                   np.array([5, 6, 7, 8], np.int32)):
        slot, first, _, _ = eng.admit(prompt, 4)
        out = [first]
        _drain_spec(eng, {slot: (out, 6)})
        eng.release(slot)
        ref = np.asarray(speculative_decode(
            model, params, model, params, jnp.asarray(prompt[None]),
            6, k=3))[0]
        assert out == ref[4:10].tolist()
    assert eng.spec_steps > 0 and eng.spec_accepted > 0
    assert eng.spec_accepted <= eng.spec_proposed
    assert eng.pool_leak_report() is None


def test_draft_arena_exhaustion_queues_cleanly():
    """A draft arena sized for ONE row: the second speculative
    admission is named-blocked on ``spec_kv_blocks`` and ``admit``
    raises EngineCapacityError BEFORE touching the pool; after the
    resident row releases, the queued request admits into the
    recycled draft blocks and its stream is exact."""
    model, params = _make_lm()
    eng = SlotDecodeEngine(model, params, slots=2, slot_len=16,
                           paged=True, kv_block_size=4,
                           spec_kv_blocks=5,      # one 4-block span
                           draft_model=model, draft_params=params,
                           spec_k=3)
    prompt_a = np.array([1, 2, 3, 4], np.int32)
    slot_a, first_a, _, _ = eng.admit(prompt_a, 4)

    prompt_b = np.array([5, 6, 7, 8], np.int32)
    assert eng.free_slots() == 1
    assert eng.admission_block_cause(prompt_b, 4) == "spec_kv_blocks"
    assert not eng.can_admit(prompt_b, 4)
    from container_engine_accelerators_tpu.models.decode import (
        EngineCapacityError,
    )
    with pytest.raises(EngineCapacityError, match="draft KV"):
        eng.admit(prompt_b, 4)
    # The refused admission mutated nothing: the free slot survives
    # and the resident row's stream is unperturbed.
    assert eng.free_slots() == 1
    out_a = [first_a]
    _drain_spec(eng, {slot_a: (out_a, 6)})
    eng.release(slot_a)

    assert eng.admission_block_cause(prompt_b, 4) is None
    slot_b, first_b, _, _ = eng.admit(prompt_b, 4)
    out_b = [first_b]
    _drain_spec(eng, {slot_b: (out_b, 6)})
    eng.release(slot_b)
    ref_b = np.asarray(greedy_decode(
        model, params, jnp.asarray(prompt_b[None]), 6))[0]
    assert out_b == ref_b[4:10].tolist()
    assert eng.pool_leak_report() is None
    stats = eng.kv_block_stats()
    assert stats["spec_kv_blocks_total"] == 4      # usable (- trash)
    assert stats["spec_kv_blocks_free"] == 4


def test_score_consumes_no_slot(lm):
    """Scoring (prompt echo logprobs) rides the prefill program only
    and matches decode(return_logprobs=True)'s echo region."""
    model, params = lm
    prompt = np.array([2, 4, 6, 8], np.int32)
    eng = SlotDecodeEngine(model, params, slots=1, slot_len=14)
    echo = eng.score(prompt, 4)
    assert eng.free_slots() == 1
    _, lps_ref = decode(model, params, jnp.asarray(prompt[None]), 1,
                        return_logprobs=True)
    np.testing.assert_allclose(echo[:4], np.asarray(lps_ref)[0][:4],
                               atol=1e-4)
