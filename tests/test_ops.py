# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Pallas kernel correctness vs jax.nn reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.ops import (
    mean_cross_entropy_loss,
    softmax_cross_entropy,
)


def reference_xent(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]


@pytest.mark.parametrize("b,c", [(8, 16), (128, 1000), (100, 130)])
def test_forward_matches_reference(b, c):
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (b, c)) * 5.0
    labels = jax.random.randint(jax.random.PRNGKey(1), (b,), 0, c)
    got = softmax_cross_entropy(logits, labels)
    want = reference_xent(logits, labels)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("b,c", [(8, 16), (64, 1000)])
def test_gradient_matches_reference(b, c):
    logits = jax.random.normal(jax.random.PRNGKey(2), (b, c))
    labels = jax.random.randint(jax.random.PRNGKey(3), (b,), 0, c)
    got = jax.grad(lambda l: jnp.mean(softmax_cross_entropy(l, labels)))(
        logits)
    want = jax.grad(lambda l: jnp.mean(reference_xent(l, labels)))(logits)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_bfloat16_logits():
    logits = (jax.random.normal(jax.random.PRNGKey(4), (16, 24))
              .astype(jnp.bfloat16))
    labels = jax.random.randint(jax.random.PRNGKey(5), (16,), 0, 24)
    got = softmax_cross_entropy(logits, labels)
    want = reference_xent(logits.astype(jnp.float32), labels)
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)
    grad = jax.grad(lambda l: jnp.mean(softmax_cross_entropy(l, labels)))(
        logits)
    assert grad.dtype == jnp.bfloat16


def test_mean_loss_jits():
    logits = jax.random.normal(jax.random.PRNGKey(6), (32, 10))
    labels = jax.random.randint(jax.random.PRNGKey(7), (32,), 0, 10)
    loss = jax.jit(mean_cross_entropy_loss)(logits, labels)
    want = float(jnp.mean(reference_xent(logits, labels)))
    assert abs(float(loss) - want) < 1e-5
