# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Model-zoo shape/param tests on the CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from container_engine_accelerators_tpu.models import (
    InceptionV3,
    MnistMLP,
    resnet,
)
from container_engine_accelerators_tpu.models.resnet import make_apply_fn


@pytest.mark.parametrize("depth,bottleneck_params", [
    (18, None), (50, None),
])
def test_resnet_forward_shape(depth, bottleneck_params):
    model = resnet(depth=depth, num_classes=10, dtype=jnp.float32, width=8)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 64, 64, 3)), train=False)
    logits = model.apply(variables, jnp.zeros((2, 64, 64, 3)), train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_resnet50_parameter_count():
    model = resnet(depth=50, num_classes=1000)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3)), train=False))
    n = sum(int(jnp.prod(jnp.array(p.shape)))
            for p in jax.tree_util.tree_leaves(variables["params"]))
    # Canonical ResNet-50 v1.5: ~25.56M params.
    assert 25_400_000 < n < 25_700_000, n


def test_resnet_train_mode_updates_batch_stats():
    model = resnet(depth=18, num_classes=4, dtype=jnp.float32, width=8)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    apply_fn = make_apply_fn(model)
    logits, new_stats = apply_fn(variables, x, True)
    assert logits.shape == (4, 4)
    old_mean = jax.tree_util.tree_leaves(variables["batch_stats"])[0]
    new_mean = jax.tree_util.tree_leaves(new_stats)[0]
    assert not jnp.allclose(old_mean, new_mean)


def test_resnet_rejects_bad_depth():
    with pytest.raises(ValueError):
        resnet(depth=42)


def test_inception_forward_shape():
    model = InceptionV3(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((1, 299, 299, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 10)
    n = sum(int(jnp.prod(jnp.array(p.shape)))
            for p in jax.tree_util.tree_leaves(variables["params"]))
    # Torch/TF Inception-v3 without aux head: ~21.8M (+fc 10 here).
    assert 21_000_000 < n < 24_000_000, n


def test_mlp_forward():
    model = MnistMLP(hidden=32, dtype=jnp.float32)
    x = jnp.zeros((8, 28, 28, 1))
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (8, 10)
