# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Deployment-manifest sanity tests.

The reference validates cluster behavior only via its demo manifests
(SURVEY.md section 4); here every shipped YAML is at least parsed and
the DaemonSet contracts (volumes, initContainer chains) are asserted,
and installer entrypoints are bash-syntax-checked.
"""

import glob
import os
import subprocess

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_all(path):
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d is not None]


def _all_yaml_paths():
    pats = ("cmd/*.yaml", "deploy/**/*.yaml", "demo/**/*.yaml",
            "example/*.yaml", "daemonset.yaml")
    out = []
    for p in pats:
        out.extend(glob.glob(os.path.join(REPO, p), recursive=True))
    return sorted(set(out))


def test_inventory_nonempty():
    paths = _all_yaml_paths()
    assert len(paths) >= 15, paths


@pytest.mark.parametrize("path", _all_yaml_paths(),
                         ids=lambda p: os.path.relpath(p, REPO))
def test_yaml_parses(path):
    docs = _load_all(path)
    assert docs, f"{path} contains no documents"
    for doc in docs:
        assert "kind" in doc and "apiVersion" in doc, path


def _daemonset(path):
    (doc,) = [d for d in _load_all(path) if d.get("kind") == "DaemonSet"]
    return doc


def test_partitioned_ds_chains_installer_then_partitioner():
    # Parity with daemonset-nvidia-mig.yaml: driver initContainer runs
    # before the partitioner initContainer, then a pause container.
    ds = _daemonset(os.path.join(
        REPO, "deploy/libtpu-installer/cos/daemonset-tpu-partitioned.yaml"))
    spec = ds["spec"]["template"]["spec"]
    inits = [c["name"] for c in spec["initContainers"]]
    assert inits == ["verify-preload", "partition-tpus"]
    assert spec["containers"][0]["name"] == "pause"
    part = spec["initContainers"][1]
    mounts = {m["mountPath"] for m in part["volumeMounts"]}
    assert {"/dev", "/run/tpu", "/etc/tpu"} <= mounts


def test_minikube_ds_provisions_sim_chips():
    ds = _daemonset(os.path.join(
        REPO, "deploy/libtpu-installer/minikube/daemonset.yaml"))
    spec = ds["spec"]["template"]["spec"]
    init = spec["initContainers"][0]
    envs = {e["name"]: e.get("value") for e in init["env"]}
    assert envs["TPU_SIM_CHIPS"] == "4"
    assert envs["TPU_SIM_TOPOLOGY"] == "2x2"
    host_paths = {v["hostPath"]["path"]
                  for v in spec["volumes"] if "hostPath" in v}
    assert {"/dev", "/run/tpu"} <= host_paths


def test_pinned_ds_pins_libtpu_version():
    ds = _daemonset(os.path.join(
        REPO, "deploy/libtpu-installer/cos/daemonset-libtpu-pinned.yaml"))
    init = ds["spec"]["template"]["spec"]["initContainers"][0]
    envs = {e["name"]: e.get("value") for e in init["env"]}
    assert envs.get("LIBTPU_VERSION")
    # The pinned path must keep the /run/tpu topology contract the
    # sibling COS manifests establish (installer publish_topology).
    mounts = {m["mountPath"] for m in init["volumeMounts"]}
    assert "/run/tpu" in mounts


@pytest.mark.parametrize("script", sorted(
    glob.glob(os.path.join(REPO, "deploy/**/*.sh"), recursive=True) +
    glob.glob(os.path.join(REPO, "build/*.sh"))),
    ids=lambda p: os.path.relpath(p, REPO))
def test_shell_scripts_parse(script):
    subprocess.run(["bash", "-n", script], check=True)


def test_minikube_provisioner_end_to_end(tmp_path):
    """Run the real entrypoint against temp dirs and verify it builds
    the exact state tree the chip backends consume."""
    dev = tmp_path / "dev"
    state = tmp_path / "state"
    dev.mkdir()
    env = dict(os.environ,
               TPU_SIM_CHIPS="4",
               TPU_SIM_TOPOLOGY="8x8",  # inconsistent: must be fixed up
               TPU_SIM_DEV_DIR=str(dev),
               TPU_SIM_STATE_DIR=str(state))
    script = os.path.join(
        REPO, "deploy/libtpu-installer/minikube/entrypoint.sh")
    out = subprocess.run(["bash", script], env=env, check=True,
                         capture_output=True, text=True).stdout
    assert "topology fixed up to 2x2" in out

    from container_engine_accelerators_tpu.chip.pyfake import (
        PyChipBackend,
    )
    be = PyChipBackend()
    be.init(str(dev), str(state))
    try:
        assert be.chip_count() == 4
        assert be.topology() == (2, 2, 1)
        assert be.chip_health(0).name == "OK"
        total, used = be.chip_hbm(0)
        assert total == 17179869184 and used == 0
    finally:
        be.shutdown()

    # Idempotency: second run is a cached no-op.
    out2 = subprocess.run(["bash", script], env=env, check=True,
                          capture_output=True, text=True).stdout
    assert "already provisioned" in out2

    # Shrink: re-provision with fewer chips removes stale ones.
    env["TPU_SIM_CHIPS"] = "1"
    env["TPU_SIM_TOPOLOGY"] = "1x1"
    subprocess.run(["bash", script], env=env, check=True,
                   capture_output=True)
    be2 = PyChipBackend()
    be2.init(str(dev), str(state))
    try:
        assert be2.chip_count() == 1
    finally:
        be2.shutdown()
