# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""v1alpha adapter tests (mirrors alpha_plugin_test.go)."""

import os

import grpc
import pytest

from container_engine_accelerators_tpu.chip import PyChipBackend
from container_engine_accelerators_tpu.plugin import api
from container_engine_accelerators_tpu.plugin import manager as manager_mod
from container_engine_accelerators_tpu.plugin.alpha_plugin import (
    register_with_kubelet,
)
from container_engine_accelerators_tpu.plugin.manager import TpuManager
from tests.plugin_helpers import KubeletStub, ServingManager, short_tmpdir


@pytest.fixture
def fast_intervals(monkeypatch):
    monkeypatch.setattr(manager_mod, "SOCKET_CHECK_INTERVAL_S", 0.1)
    monkeypatch.setattr(manager_mod, "CHIP_CHECK_INTERVAL_S", 0.5)


def make_manager(node):
    for i in range(4):
        node.add_chip(i)
    node.set_topology("2x2")
    m = TpuManager(dev_dir=node.dev_dir, state_dir=node.state_dir,
                   backend=PyChipBackend(),
                   mount_paths=[("/usr/local/tpu", "/tmp/host-tpu")])
    m.start()
    return m


def test_register_v1alpha(fake_node):
    plugin_dir = short_tmpdir()
    sock = os.path.join(plugin_dir, "kubelet.sock")
    stub = KubeletStub(sock)
    stub.start()
    try:
        register_with_kubelet(sock, "tpu-123.sock", "google.com/tpu")
        assert stub.requests[0].version == api.V1ALPHA_VERSION
        assert stub.requests[0].endpoint == "tpu-123.sock"
    finally:
        stub.stop()


def test_alpha_list_and_watch_and_allocate(fake_node, fast_intervals):
    plugin_dir = short_tmpdir()
    with ServingManager(make_manager(fake_node), plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1AlphaStub(ch)
            first = next(iter(stub.ListAndWatch(api.v1alpha_pb2.Empty())))
            assert len(first.devices) == 4

            resp = stub.Allocate(api.v1alpha_pb2.AllocateRequest(
                devicesIDs=["accel0", "accel1", "accel2", "accel3"]))
            assert len(resp.devices) == 4
            assert resp.envs["TPU_VISIBLE_DEVICES"] == "0,1,2,3"
            assert resp.envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
            assert len(resp.mounts) == 1
            assert resp.mounts[0].container_path == "/usr/local/tpu"
            assert resp.mounts[0].read_only


def test_alpha_allocate_unknown_fails(fake_node, fast_intervals):
    plugin_dir = short_tmpdir()
    with ServingManager(make_manager(fake_node), plugin_dir) as sm:
        with sm.channel() as ch:
            stub = api.DevicePluginV1AlphaStub(ch)
            with pytest.raises(grpc.RpcError) as err:
                stub.Allocate(
                    api.v1alpha_pb2.AllocateRequest(devicesIDs=["accel7"]))
            assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
