# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""wall_sync: the async-backend-proof completion barrier."""

import jax
import jax.numpy as jnp
import numpy as np

from container_engine_accelerators_tpu.utils.sync import wall_sync


def test_returns_first_scalar():
    x = jnp.arange(6.0).reshape(2, 3) + 1.0
    assert wall_sync(x) == 1.0


def test_tree_returns_first_leaf_scalar():
    tree = {"a": jnp.full((3,), 7.0), "b": jnp.zeros((2, 2))}
    first = wall_sync(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    assert first == np.ravel(np.asarray(leaves[0]))[0]


def test_empty_and_sizeless_trees():
    assert wall_sync({}) is None
    assert wall_sync(jnp.zeros((0,))) is None
    assert wall_sync([jnp.zeros((0,)), jnp.full((1,), 3.0)]) == 3.0


def test_forces_computation_of_jitted_output():
    out = jax.jit(lambda x: x * 2 + 1)(jnp.ones((4, 4)))
    assert wall_sync(out) == 3.0


def test_non_array_leaves_are_skipped():
    assert wall_sync({"n": 5, "s": "x", "a": jnp.full((2,), 9.0)}) == 9.0
