# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The analysis suite's own tests: lint rules against seeded
fixtures (each fires exactly where expected, escapes respected), the
tree-is-clean tier-1 gate, the tsan shim against a deliberate
lock-order inversion, and the retrace guard against a deliberately
retracing jit function."""

import subprocess
import sys
import threading

import pytest

from container_engine_accelerators_tpu.analysis import (
    run_lint,
    tsan,
)
from container_engine_accelerators_tpu.analysis.lint import (
    Project,
    verify_fixtures,
)
from container_engine_accelerators_tpu.analysis.retrace import (
    RetraceError,
    RetraceGuard,
)
from container_engine_accelerators_tpu.analysis.selfcheck import (
    inverted_lock_report,
    mixed_traffic_compile_counts,
    run_serialized,
    seeded_retracer_caught,
)
from tests.conftest import REPO_ROOT

FIXTURES = "tests/fixtures/analysis"


# -- lint -------------------------------------------------------------


def test_tree_is_lint_clean():
    """The tier-1 drift gate: zero findings over the default scope
    (package, tools/, cmd/, demo/). A convention violation fails CI
    the moment it lands, not at the next review."""
    findings = run_lint(root=REPO_ROOT)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_fixtures_fire_exactly_as_seeded():
    """Every seeded violation fires on exactly its EXPECT line; no
    rule fires anywhere else in the fixture tree (which also pins
    the `# lint: disable=` escape behavior — the escaped lines carry
    the same violations un-annotated)."""
    missing, unexpected = verify_fixtures(FIXTURES, root=REPO_ROOT)
    assert missing == [], f"seeded violations did not fire: {missing}"
    assert unexpected == [], f"unexpected findings: {unexpected}"


def test_disable_comment_is_line_scoped(tmp_path):
    """A disable comment suppresses its own line only."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import threading\n"
        "L = threading.Lock()\n"
        "L.acquire()  # lint: disable=lock-with\n"
        "L.acquire()\n")
    findings = run_lint(paths=[str(mod)], root=str(tmp_path),
                        project=Project(REPO_ROOT))
    assert [(f.rule, f.line) for f in findings] == [("lock-with", 4)]


def test_disable_file_suppresses_whole_module(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text(
        "# lint: disable-file=lock-with\n"
        "import threading\n"
        "L = threading.Lock()\n"
        "L.acquire()\n"
        "L.acquire()\n")
    findings = run_lint(paths=[str(mod)], root=str(tmp_path),
                        project=Project(REPO_ROOT))
    assert findings == []


def test_unknown_expect_rule_id_is_a_hard_error(tmp_path):
    """An EXPECT naming a rule NEITHER verifier (lint or IR) knows
    must raise, not silently drop — a typo'd id would otherwise
    leave its seeded violation verified by nothing."""
    mod = tmp_path / "f.py"
    mod.write_text(
        "import threading\n"
        "L = threading.Lock()\n"
        "L.acquire()  # EXPECT: lock-withh\n")
    with pytest.raises(ValueError, match="unknown rule"):
        verify_fixtures(str(tmp_path), root=REPO_ROOT)


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    mod = tmp_path / "broken.py"
    mod.write_text("def f(:\n")
    findings = run_lint(paths=[str(mod)], root=str(tmp_path),
                        project=Project(REPO_ROOT))
    assert [f.rule for f in findings] == ["syntax-error"]


def test_jax_free_transitive_walk():
    """The import-graph walk sees through one hop: a jax-free module
    importing a package module that imports jax at module scope is
    flagged even though 'jax' never appears in its own source. The
    real tree is clean, so assert on the graph mechanics instead:
    utils.sync (the deliberate jax importer) is IN the graph and
    reached by nothing in the jax-free packages."""
    project = Project(REPO_ROOT)
    graph = project.import_graph
    sync = "container_engine_accelerators_tpu.utils.sync"
    assert any(dep == "jax" for dep, _ in graph[sync])
    jax_free_prefixes = tuple(
        f"container_engine_accelerators_tpu.{p}"
        for p in ("obs", "plugin", "chip", "analysis"))
    importers = [mod for mod, deps in graph.items()
                 if mod.startswith(jax_free_prefixes)
                 and any(dep == sync for dep, _ in deps)]
    assert importers == []


def test_cli_reports_findings_and_exit_code():
    proc = subprocess.run(
        [sys.executable, "-m",
         "container_engine_accelerators_tpu.analysis",
         FIXTURES],
        cwd=REPO_ROOT, capture_output=True, text=True)
    assert proc.returncode == 1
    assert "[metric-registry]" in proc.stdout
    assert "[jax-free-import]" in proc.stdout


# -- tsan -------------------------------------------------------------

_run_serialized = run_serialized


def test_tsan_flags_inverted_lock_order():
    """Two threads taking (a, b) and (b, a): a cycle in the order
    graph — the deadlock-in-waiting the shim exists to catch — with
    both creation sites named. Shared with `make analysis-check`
    (analysis.selfcheck), so the gate and this test cannot drift."""
    rep = inverted_lock_report()
    assert len(rep["cycles"]) == 1
    sites = rep["cycles"][0]["sites"]
    assert all("selfcheck.py" in s for s in sites)
    assert not tsan.enabled()


def test_tsan_clean_on_consistent_order():
    with tsan.session(force=True) as state:
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def ordered():
            with lock_a:
                with lock_b:
                    pass

        _run_serialized(ordered, ordered)
        rep = state.report()
    assert rep["cycles"] == []
    assert rep["edges"] == 1


def test_tsan_unguarded_write_is_flagged_guarded_is_not():
    class Owner:
        pass

    bad, good = Owner(), Owner()
    with tsan.session(force=True) as state:
        guard_lock = threading.Lock()

        def unguarded():
            tsan.note_write("fixture.table", bad)

        def guarded():
            with guard_lock:
                tsan.note_write("fixture.table", good)

        _run_serialized(unguarded, unguarded, guarded, guarded)
        rep = state.report()
    names = [w["name"] for w in rep["unguarded_writes"]]
    assert names == ["fixture.table"]
    # ... and the finding came from the unguarded owner: re-run with
    # only the guarded pattern.
    with tsan.session(force=True) as state:
        guard_lock = threading.Lock()
        owner = Owner()

        def guarded2():
            with guard_lock:
                tsan.note_write("fixture.table2", owner)

        _run_serialized(guarded2, guarded2)
        assert state.report()["unguarded_writes"] == []


def test_tsan_per_instance_write_scoping():
    """Two instances, each single-threaded from different threads:
    clean. Pooling them under one global name would false-positive
    (the bug the checkpoint suite caught in this shim's first
    draft)."""
    class Owner:
        pass

    first, second = Owner(), Owner()
    with tsan.session(force=True) as state:
        def t1():
            tsan.note_write("fixture.pool", first)

        def t2():
            tsan.note_write("fixture.pool", second)

        _run_serialized(t1, t2)
        assert state.report()["unguarded_writes"] == []


def test_tsan_recursive_lock_acquire_raises():
    with tsan.session(force=True) as state:
        lock = threading.Lock()
        with lock:
            with pytest.raises(RuntimeError, match="re-acquire"):
                lock.acquire()  # lint: disable=lock-with
        rep = state.report()
    assert len(rep["recursive_acquires"]) == 1
    # RLock re-entry stays legal.
    with tsan.session(force=True) as state:
        rlock = threading.RLock()
        with rlock:
            with rlock:
                pass
        assert state.report()["recursive_acquires"] == []


def test_tsan_uninstall_restores_real_primitives():
    with tsan.session(force=True):
        assert type(threading.Lock()).__name__ == "_SanLock"
    assert type(threading.Lock()).__name__ != "_SanLock"
    assert not tsan.enabled()


def test_tsan_condition_on_rlock_wait_notify():
    """Condition() with NO lock allocates an RLock — wrapped under
    the shim — and must still wait/notify correctly through the
    Condition protocol (_is_owned/_release_save/_acquire_restore on
    the wrapper; the stdlib acquire(False) ownership probe would
    wrongly succeed on a held re-entrant lock)."""
    with tsan.session(force=True):
        cond = threading.Condition()   # default RLock, wrapped
        assert type(cond._lock).__name__ == "_SanRLock"
        fired = []

        def waiter():
            with cond:
                while not fired:
                    cond.wait(timeout=1)

        t = threading.Thread(target=waiter)
        t.start()
        with cond:
            fired.append(1)
            cond.notify_all()
        t.join(timeout=2)
        assert not t.is_alive()
        # Depth-2 wait: _release_save must drop the FULL recursion
        # depth and _acquire_restore must restore it.
        with cond:
            with cond._lock:
                assert not cond.wait(timeout=0.05)  # times out, ok
            assert cond._lock._is_owned()


def test_tsan_timed_reacquire_is_not_flagged():
    """acquire(timeout=N) on a lock the thread already holds is a
    legal checked probe (it returns False at the deadline), NOT a
    certain deadlock — the shim must not raise."""
    with tsan.session(force=True) as state:
        lock = threading.Lock()
        with lock:
            assert lock.acquire(timeout=0.05) is False
        assert state.report()["recursive_acquires"] == []


def test_tsan_condition_and_queue_still_work():
    """The wrapped primitives must stay drop-in for the stdlib
    machinery the repo leans on (Condition-on-Lock in the checkpoint
    manager, queue.Queue in serving)."""
    import queue as queue_mod

    with tsan.session(force=True):
        lock = threading.Lock()
        cond = threading.Condition(lock)
        fired = []

        def waiter():
            with cond:
                while not fired:
                    cond.wait(timeout=1)

        t = threading.Thread(target=waiter)
        t.start()
        with cond:
            fired.append(1)
            cond.notify_all()
        t.join(timeout=2)
        assert not t.is_alive()

        q = queue_mod.Queue()
        q.put(1)
        assert q.get(timeout=1) == 1


# -- retrace ----------------------------------------------------------


def test_retrace_guard_catches_seeded_retracer():
    """The analysis-check fixture, shared via analysis.selfcheck."""
    assert seeded_retracer_caught()
    # And the error itself names the offending program.
    import jax
    import jax.numpy as jnp

    @jax.jit
    def leaky(x):
        return x * 2

    guard = RetraceGuard().watch("leaky", leaky, max_new=1)
    with pytest.raises(RetraceError, match="leaky"):
        with guard:
            for width in range(1, 5):
                leaky(jnp.zeros((width,), jnp.float32))


def test_retrace_guard_passes_within_budget():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def stable(x):
        return x + 1

    with RetraceGuard().watch("stable", stable, max_new=1) as guard:
        for _ in range(5):
            stable(jnp.zeros((3,), jnp.float32))
    assert guard.new_compiles() == {"stable": 1}


def test_retrace_watch_rejects_unjitted():
    with pytest.raises(TypeError, match="_cache_size"):
        RetraceGuard().watch("plain", lambda x: x)


def test_retrace_late_watch_baselines_at_watch_time():
    """watch() inside an OPEN guard baselines the cache size at that
    moment — compiles that happened earlier in the region are not
    charged against the late watch's budget."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def leaky(x):
        return x * 2

    with RetraceGuard() as guard:
        leaky(jnp.zeros((1,), jnp.float32))   # pre-watch compile
        leaky(jnp.zeros((2,), jnp.float32))   # pre-watch compile
        guard.watch("late", leaky, max_new=1)
        leaky(jnp.zeros((3,), jnp.float32))   # 1 new: inside budget
    assert guard.new_compiles() == {"late": 1}
    # ...and the budget still bites on post-watch compiles.
    with pytest.raises(RetraceError, match="late"):
        with RetraceGuard() as guard:
            leaky(jnp.zeros((4,), jnp.float32))
            guard.watch("late", leaky, max_new=1)
            leaky(jnp.zeros((5,), jnp.float32))
            leaky(jnp.zeros((6,), jnp.float32))


def test_retrace_exit_with_active_exception_skips_check():
    """__exit__ under an in-flight exception must NOT stack a
    RetraceError on top — the region's real failure propagates, and
    new_compiles() stays queryable for post-mortem."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def leaky(x):
        return x + 1

    guard = RetraceGuard().watch("leaky", leaky, max_new=1)
    with pytest.raises(ValueError, match="boom"):
        with guard:
            for width in range(1, 4):     # blows the budget...
                leaky(jnp.zeros((width,), jnp.float32))
            raise ValueError("boom")      # ...but this is the error
    assert guard.new_compiles() == {"leaky": 3}
    with pytest.raises(RetraceError):
        guard.check()


def test_engine_guard_holds_on_mixed_traffic():
    """The acceptance bound, in-tree and SHARED with `make
    analysis-check` (analysis.selfcheck): a bucketed paged engine
    serves greedy + filtered + penalty + shared/forked traffic
    across block boundaries inside prefill(=1 bucket) + insert +
    step."""
    counts = mixed_traffic_compile_counts()
    assert counts["engine.paged_insert"] <= 1
    assert counts["engine.paged_step"] <= 1
    assert counts["engine.paged_prefill"] <= 1
