# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Multi-host slice end-to-end: plugin env contract -> jax.distributed.

The reference never faces this (NCCL setup is the workload's problem);
for TPU the plugin's Allocate response is what lets JAX initialize
collectives across hosts (SURVEY.md section 7, "Allocate-time env
composition"). These tests simulate a 2-host x 4-chip slice: one
TpuManager per host (as one plugin runs per host), and the exported
env contract must be sufficient to boot jax.distributed and run a
sharded pjit step spanning all 8 devices — executed here as two real
processes on the virtual CPU mesh, 4 local devices each.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from container_engine_accelerators_tpu.chip.pyfake import PyChipBackend
from container_engine_accelerators_tpu.plugin.envs import (
    parse_process_bounds,
    topology_envs,
)
from container_engine_accelerators_tpu.plugin.manager import TpuManager

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _host_manager(fake_node, worker_id, hostnames, process_bounds=None):
    mgr = TpuManager(
        dev_dir=fake_node.dev_dir, state_dir=fake_node.state_dir,
        backend=PyChipBackend(), worker_id=worker_id,
        worker_hostnames=hostnames, process_bounds=process_bounds)
    mgr.start()
    return mgr


def _two_host_envs(fake_node, process_bounds=None):
    """Env contracts for host 0 and host 1 of a 2-host x 4-chip slice.

    Each host's plugin sees only its local 4 chips (a 2x2 tile of the
    global 2x4 slice); worker identity distinguishes the hosts.
    """
    for i in range(4):
        fake_node.add_chip(i)
    fake_node.set_topology("2x2x1")
    hostnames = ("host0", "host1")
    out = []
    for wid in (0, 1):
        mgr = _host_manager(fake_node, wid, hostnames, process_bounds)
        out.append(mgr.allocate_envs([f"accel{i}" for i in range(4)]))
    return out


def test_env_contract_two_hosts(fake_node):
    envs0, envs1 = _two_host_envs(fake_node)
    for wid, envs in enumerate((envs0, envs1)):
        assert envs["TPU_VISIBLE_DEVICES"] == "0,1,2,3"
        assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
        assert envs["TPU_PROCESS_BOUNDS"] == "1,1,2"
        assert envs["TPU_WORKER_ID"] == str(wid)
        assert envs["CLOUD_TPU_TASK_ID"] == str(wid)
        assert envs["TPU_WORKER_HOSTNAMES"] == "host0,host1"


def test_env_contract_nonlinear_process_bounds(fake_node):
    envs0, envs1 = _two_host_envs(fake_node, process_bounds=(2, 1, 1))
    assert envs0["TPU_PROCESS_BOUNDS"] == "2,1,1"
    assert envs1["TPU_PROCESS_BOUNDS"] == "2,1,1"


def test_process_bounds_must_cover_workers(fake_node):
    with pytest.raises(ValueError):
        _host_manager(fake_node, 0, ("host0", "host1"),
                      process_bounds=(2, 2, 1))


def test_parse_process_bounds():
    assert parse_process_bounds("2,2,1") == (2, 2, 1)
    assert parse_process_bounds("2x2x1") == (2, 2, 1)
    assert parse_process_bounds("4") == (4, 1, 1)
    assert parse_process_bounds("2,2") == (2, 2, 1)
    for bad in ("", "1,2,3,4", "a,b", "0,1,1"):
        with pytest.raises(ValueError):
            parse_process_bounds(bad)


def test_topology_envs_rejects_short_bounds():
    with pytest.raises(ValueError):
        topology_envs([0], [(0, 0, 0)], worker_hostnames=("h0", "h1", "h2"),
                      process_bounds=(2, 1, 1))


_WORKER_SCRIPT = textwrap.dedent("""
    import json, os, sys

    sys.path.insert(0, "@REPO_ROOT@")

    # Everything below derives from the plugin's Allocate env contract.
    wid = int(os.environ["TPU_WORKER_ID"])
    hosts = os.environ["TPU_WORKER_HOSTNAMES"].split(",")
    local_chips = os.environ["TPU_VISIBLE_DEVICES"].split(",")
    port = sys.argv[1]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=%d" % len(local_chips))
    import jax
    jax.config.update("jax_platforms", "cpu")
    # The framework's own bootstrap consumes the contract; the test
    # redirects the coordinator to loopback via the env override the
    # helper documents (hostnames are not resolvable in this harness).
    os.environ["CEA_COORDINATOR_ADDRESS"] = "127.0.0.1:" + port
    from container_engine_accelerators_tpu.parallel.distributed import (
        initialize_from_plugin_env,
    )
    assert initialize_from_plugin_env() is True

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    assert len(devs) == len(hosts) * len(local_chips), devs
    assert len(jax.local_devices()) == len(local_chips)
    mesh = Mesh(
        np.array(devs).reshape(len(hosts), len(local_chips)),
        ("host", "chip"))
    sharding = NamedSharding(mesh, P(("host", "chip")))

    n = len(devs) * 2
    data = np.arange(n, dtype=np.float32)
    x = jax.make_array_from_callback(
        (n,), sharding, lambda idx: data[idx])
    y = jax.jit(lambda a: jnp.sum(a * 2.0),
                out_shardings=NamedSharding(mesh, P()))(x)
    print(json.dumps({"worker": wid, "sum": float(y)}), flush=True)
""")


_GRID_WORKER_SCRIPT = textwrap.dedent("""
    import json, os, sys

    sys.path.insert(0, "@REPO_ROOT@")

    wid = int(os.environ["TPU_WORKER_ID"])
    hosts = os.environ["TPU_WORKER_HOSTNAMES"].split(",")
    local_chips = os.environ["TPU_VISIBLE_DEVICES"].split(",")
    port = sys.argv[1]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=%d" % len(local_chips))
    import jax
    jax.config.update("jax_platforms", "cpu")
    os.environ["CEA_COORDINATOR_ADDRESS"] = "127.0.0.1:" + port
    from container_engine_accelerators_tpu.parallel.distributed import (
        initialize_from_plugin_env,
    )
    from container_engine_accelerators_tpu.plugin.envs import (
        parse_process_bounds,
    )
    from container_engine_accelerators_tpu.parallel import (
        HOST_AXES,
        host_grid_mesh,
    )
    assert initialize_from_plugin_env() is True

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    bounds = parse_process_bounds(os.environ["TPU_PROCESS_BOUNDS"])
    assert bounds == (2, 2, 1), bounds
    mesh = host_grid_mesh(bounds)
    px, py, pz = bounds
    # Every mesh cell's device must belong to the process the grid
    # math places there (row-major process order).
    for x in range(px):
        for y in range(py):
            for z in range(pz):
                dev = mesh.devices[x, y, z, 0]
                assert dev.process_index == (x * py + y) * pz + z, (
                    (x, y, z), dev)

    axes = HOST_AXES + ("chip",)
    sharding = NamedSharding(mesh, P(axes))
    n = mesh.size * 2
    data = np.arange(n, dtype=np.float32)
    x = jax.make_array_from_callback(
        (n,), sharding, lambda idx: data[idx])
    y = jax.jit(lambda a: jnp.sum(a * 2.0),
                out_shardings=NamedSharding(mesh, P()))(x)
    print(json.dumps({"worker": wid, "sum": float(y)}), flush=True)
""")


@pytest.mark.slow
def test_four_process_2x2_grid_pjit_step(fake_node, tmp_path):
    """Non-linear host grids end-to-end (VERDICT r2 #8): four real
    processes boot jax.distributed purely from the plugin's Allocate
    env contract with --tpu-process-bounds 2,2, build the 2x2x1 host
    grid mesh, verify device placement matches the grid math, and run
    a pjit reduction over all 8 devices."""
    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("2x1x1")
    hostnames = tuple(f"host{i}" for i in range(4))
    env_sets = []
    for wid in range(4):
        mgr = _host_manager(fake_node, wid, hostnames,
                            process_bounds=(2, 2, 1))
        envs = mgr.allocate_envs(["accel0", "accel1"])
        assert envs["TPU_PROCESS_BOUNDS"] == "2,2,1"
        env_sets.append(envs)

    script = tmp_path / "grid_worker.py"
    script.write_text(
        _GRID_WORKER_SCRIPT.replace("@REPO_ROOT@", REPO_ROOT))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])

    procs = []
    for envs in env_sets:
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("TPU_", "XLA_", "JAX_"))}
        env.update(envs)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))

    results = {}
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, err.decode()[-2000:]
        line = json.loads(out.decode().strip().splitlines()[-1])
        results[line["worker"]] = line["sum"]

    n = 16  # 8 devices x 2 elements
    expected = float(2 * sum(range(n)))
    assert results == {w: expected for w in range(4)}


@pytest.mark.slow
def test_two_process_pjit_step(fake_node, tmp_path):
    """Boot two real processes from the plugin env contract and run a
    pjit reduction over the global 2x4 device mesh."""
    envs0, envs1 = _two_host_envs(fake_node)
    script = tmp_path / "worker.py"
    script.write_text(_WORKER_SCRIPT.replace("@REPO_ROOT@", REPO_ROOT))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])

    procs = []
    for envs in (envs0, envs1):
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("TPU_", "XLA_", "JAX_"))}
        env.update(envs)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))

    results = {}
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err.decode()[-2000:]
        line = json.loads(out.decode().strip().splitlines()[-1])
        results[line["worker"]] = line["sum"]

    n = 16  # 8 devices x 2 elements
    expected = float(2 * sum(range(n)))
    assert results == {0: expected, 1: expected}
