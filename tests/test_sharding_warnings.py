# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The dp+sp+ep composition must compile without SPMD distress.

Round-1 verdict: the MoE (data, context, expert) train step compiled
with repeated "Involuntary full rematerialization" warnings — XLA
replicating LayerNorm/attention gradient tensors because the residual
stream had no explicit sharding while the MoE dispatch pinned its
tokens to a fully-sharded layout. These tests compile the composed
step with fd-level stderr capture and fail on any recurrence.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from container_engine_accelerators_tpu.models import MoETransformerLM
from container_engine_accelerators_tpu.models import moe as moe_mod
from container_engine_accelerators_tpu.models.transformer import (
    next_token_loss_fn,
)
from container_engine_accelerators_tpu.parallel import (
    Trainer,
    batch_sharding,
    ring_attention,
)
from container_engine_accelerators_tpu.parallel.context import CONTEXT_AXIS
from container_engine_accelerators_tpu.parallel.expert import EXPERT_AXIS
from container_engine_accelerators_tpu.parallel.mesh import DATA_AXIS
from container_engine_accelerators_tpu.parallel.train import (
    cross_entropy_loss,
)
from container_engine_accelerators_tpu.utils.xla_warnings import (
    capture_stderr_fd,
    check_no_resharding,
    find_resharding_warnings,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")


def _moe_step_log():
    """Compile one dp+sp+ep MoE train step, returning the stderr log."""
    from jax.sharding import Mesh

    devices = jax.devices()[:8]
    mesh3 = Mesh(np.array(devices).reshape(2, 2, 2),
                 (DATA_AXIS, CONTEXT_AXIS, EXPERT_AXIS))
    attn = functools.partial(ring_attention, mesh3,
                             axis_name=CONTEXT_AXIS,
                             batch_axis=DATA_AXIS)
    lm = MoETransformerLM(
        vocab_size=32, embed_dim=32, num_layers=2, num_heads=4,
        num_experts=4, max_seq_len=16, dtype=jnp.float32,
        attention_fn=attn, mesh=mesh3)
    trainer = Trainer(
        moe_mod.make_apply_fn(lm),
        moe_mod.with_router_loss(next_token_loss_fn(cross_entropy_loss)),
        optax.adam(1e-3), mesh=mesh3)

    with capture_stderr_fd(echo=False) as cap:
        tokens = jnp.zeros((8, 16), jnp.int32)
        variables = lm.init(jax.random.PRNGKey(0), tokens)
        state = trainer.init_state(variables)
        batch = (jax.device_put(tokens, batch_sharding(mesh3)),
                 jax.device_put(tokens, batch_sharding(mesh3)))
        state, loss = trainer.train_step(state, batch)
        jax.block_until_ready(loss)
    return cap.text


def test_moe_dp_sp_ep_compiles_without_full_remat():
    log = _moe_step_log()
    check_no_resharding(log, context="dp+sp+ep MoE train step")


def test_find_resharding_warnings_detects_phrase():
    log = ("something fine\n"
           "2026-01-01 spmd_partitioner.cc: Involuntary full "
           "rematerialization for add_any\nmore\n")
    assert len(find_resharding_warnings(log)) == 1
    with pytest.raises(RuntimeError, match="rematerialization"):
        check_no_resharding(log)
    check_no_resharding("clean compile log")
