# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Input pipeline: PrefetchLoader + NpzShardDataset on the CPU mesh."""

import numpy as np
import pytest

from container_engine_accelerators_tpu.parallel import (
    NpzShardDataset,
    PrefetchLoader,
    batch_sharding,
    build_mesh,
)
from container_engine_accelerators_tpu.parallel.mesh import default_spec


def _shards(tmp_path, sizes, dim=4, classes=10):
    """Write .npz shards with globally increasing labels for ordering
    checks; images[i] encodes its global index."""
    idx = 0
    for s, size in enumerate(sizes):
        images = np.stack([np.full((dim,), idx + i, np.float32)
                           for i in range(size)])
        labels = np.arange(idx, idx + size, dtype=np.int32) % classes
        np.savez(tmp_path / f"shard{s}.npz", images=images, labels=labels)
        idx += size
    return str(tmp_path)


def test_prefetch_preserves_order_and_values():
    source = [(np.full((2, 3), i, np.float32),
               np.full((2,), i, np.int32)) for i in range(7)]
    out = list(PrefetchLoader(iter(source)))
    assert len(out) == 7
    for i, (images, labels) in enumerate(out):
        np.testing.assert_array_equal(np.asarray(images), source[i][0])
        np.testing.assert_array_equal(np.asarray(labels), source[i][1])


def test_prefetch_wait_cb_reports_consumer_waits():
    """wait_cb (the Trainer.record_data_wait seam) sees one wait
    duration per consumed batch — the per-host data-starvation
    signal behind train.step_summary events."""
    import time

    def slow_source():
        for i in range(3):
            time.sleep(0.02)
            yield (np.full((1,), i, np.float32),
                   np.full((1,), i, np.int32))

    waits = []
    out = list(PrefetchLoader(slow_source(), wait_cb=waits.append))
    assert len(out) == 3
    assert len(waits) >= 3  # one per batch (+ the DONE sentinel read)
    assert all(w >= 0 for w in waits)
    assert sum(waits) > 0  # the staged source made the consumer wait


def test_prefetch_device_puts_to_sharding():
    import jax

    mesh = build_mesh(default_spec(8))
    sharding = batch_sharding(mesh)
    source = [(np.ones((16, 3), np.float32), np.ones((16,), np.int32))]
    (images, labels), = list(PrefetchLoader(iter(source),
                                            sharding=sharding))
    assert isinstance(images, jax.Array)
    assert images.sharding.is_equivalent_to(sharding, images.ndim)


def test_prefetch_propagates_source_error():
    def bad():
        yield (np.zeros(2), np.zeros(2))
        raise RuntimeError("disk on fire")

    it = PrefetchLoader(bad())
    next(it)
    with pytest.raises(RuntimeError, match="disk on fire"):
        next(it)


def test_prefetch_rejects_bad_depth():
    with pytest.raises(ValueError):
        PrefetchLoader(iter([]), prefetch=0)


def test_prefetch_error_is_sticky_not_deadlock():
    def bad():
        raise RuntimeError("boom")
        yield  # pragma: no cover

    it = PrefetchLoader(bad())
    for _ in range(3):  # every retry re-raises; never blocks
        with pytest.raises(RuntimeError, match="boom"):
            next(it)


def test_prefetch_close_releases_stage_thread():
    def infinite():
        i = 0
        while True:
            yield (np.full((2,), i, np.float32),)
            i += 1

    loader = PrefetchLoader(infinite(), prefetch=2)
    next(loader)
    loader.close()
    assert not loader._thread.is_alive()
    with pytest.raises(StopIteration):
        next(loader)


def test_prefetch_context_manager_closes():
    with PrefetchLoader(iter([(np.zeros(2),)] * 100)) as loader:
        next(loader)
    assert not loader._thread.is_alive()


def test_npz_shards_batches_span_shard_boundaries(tmp_path):
    # 5 + 3 + 6 = 14 samples; batch 4 -> 3 batches/epoch, 2 dropped.
    data_dir = _shards(tmp_path, [5, 3, 6])
    batches = list(NpzShardDataset(data_dir, batch_size=4, epochs=1))
    assert len(batches) == 3
    seen = np.concatenate([b[0][:, 0] for b in batches])
    # Every yielded sample is distinct and self-consistent.
    assert len(set(seen.tolist())) == 12
    for images, labels in batches:
        assert images.shape == (4, 4)
        assert labels.shape == (4,)
        np.testing.assert_array_equal(images[:, 0].astype(np.int32) % 10,
                                      labels)


def test_npz_shards_epochs_and_determinism(tmp_path):
    data_dir = _shards(tmp_path, [4, 4])
    two = list(NpzShardDataset(data_dir, batch_size=4, epochs=2))
    assert len(two) == 4
    again = list(NpzShardDataset(data_dir, batch_size=4, epochs=2))
    for (a, _), (b, _) in zip(two, again):
        np.testing.assert_array_equal(a, b)


def test_npz_shards_no_duplicates_across_epochs(tmp_path):
    # 14 samples, batch 4: the 2-sample tail must be DROPPED at the
    # epoch boundary, not carried over (which would re-yield those
    # samples when their shard is re-read next epoch).
    data_dir = _shards(tmp_path, [5, 3, 6])
    batches = list(NpzShardDataset(data_dir, batch_size=4, epochs=2))
    assert len(batches) == 6  # 3 full batches per epoch
    per_epoch = [np.concatenate([b[0][:, 0] for b in batches[:3]]),
                 np.concatenate([b[0][:, 0] for b in batches[3:]])]
    for seen in per_epoch:
        assert len(set(seen.tolist())) == 12  # no dupes inside epoch


def test_npz_shards_missing_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        NpzShardDataset(str(tmp_path), batch_size=2)


@pytest.mark.slow
def test_train_driver_resnet_real_data(tmp_path):
    """The resnet CLI path end-to-end with .npz shards — regression
    for the models-package name shadowing that broke
    `--model resnet` (function `resnet` hid the submodule), which no
    other test drove."""
    import importlib.util

    rng = np.random.default_rng(0)
    for s in range(2):
        np.savez(tmp_path / f"s{s}.npz",
                 images=rng.standard_normal(
                     (24, 32, 32, 3)).astype(np.float32),
                 labels=rng.integers(0, 10, size=(24,), dtype=np.int32))
    spec = importlib.util.spec_from_file_location(
        "demo_train_resnet", "demo/tpu-training/train.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    result = mod.main([
        "--model", "resnet", "--depth", "18", "--image-size", "32",
        "--num-classes", "10", "--batch-size", "16", "--steps", "2",
        "--warmup-steps", "0", "--data-dir", str(tmp_path)])
    assert np.isfinite(result["final_loss"])


def test_file_pipeline_feeds_trainer(tmp_path):
    """NpzShardDataset -> PrefetchLoader -> one sharded train step."""
    import jax
    import jax.numpy as jnp
    import optax

    from container_engine_accelerators_tpu.parallel import Trainer

    dim, classes = 8, 4
    data_dir = _shards(tmp_path, [20, 20], dim=dim, classes=classes)
    mesh = build_mesh(default_spec(8))

    def apply_fn(variables, x, train, *_):
        return x @ variables["params"]["w"], {}

    def loss_fn(logits, labels):
        onehot = jax.nn.one_hot(labels, classes)
        return -jnp.mean(jnp.sum(
            onehot * jax.nn.log_softmax(logits), axis=-1))

    trainer = Trainer(apply_fn, loss_fn, optax.sgd(0.1), mesh=mesh)
    state = trainer.init_state(
        {"params": {"w": jnp.zeros((dim, classes), jnp.float32)}})
    loader = PrefetchLoader(
        NpzShardDataset(data_dir, batch_size=16, epochs=1),
        sharding=batch_sharding(mesh))
    steps = 0
    for batch in loader:
        state, loss = trainer.train_step(state, batch)
        steps += 1
    assert steps == 2  # 40 samples / 16 -> 2 full batches
    assert float(state.step) == 2
    assert np.isfinite(float(loss))


def test_npz_skip_batches_exact_with_aligned_shards(tmp_path):
    """Shards whose sizes are batch multiples: skip_batches=k resumes
    the stream exactly at batch k (checkpoint-resume contract)."""
    data_dir = _shards(tmp_path, [8, 8, 8])
    full = list(NpzShardDataset(data_dir, batch_size=4, epochs=1))
    for k in (1, 2, 3, 5):
        resumed = list(NpzShardDataset(data_dir, batch_size=4,
                                       epochs=1, skip_batches=k))
        assert len(resumed) == len(full) - k
        for (gi, gl), (wi, wl) in zip(resumed, full[k:]):
            np.testing.assert_array_equal(gi, wi)
            np.testing.assert_array_equal(gl, wl)


def test_npz_skip_batches_header_only_shard_skip(tmp_path):
    """Whole skipped shards are only header-read; the remaining
    stream is deterministic and never re-yields skipped samples."""
    from unittest import mock

    from container_engine_accelerators_tpu.parallel import data as D

    data_dir = _shards(tmp_path, [8, 8, 8])
    loaded = []
    real_load = np.load

    def spy_load(path, *a, **kw):
        loaded.append(str(path))
        return real_load(path, *a, **kw)

    with mock.patch.object(D.np, "load", side_effect=spy_load):
        out = list(D.NpzShardDataset(data_dir, batch_size=4,
                                     epochs=1, skip_batches=2))
    # 2 batches = the first whole shard in this epoch's order: it
    # must not have been np.load-ed (header path only).
    assert len(out) == 4
    assert len(loaded) == 2


def test_npz_skip_batches_unaligned_is_shard_conservative(tmp_path):
    """Non-multiple shard sizes: skipping stays shard-aligned in its
    accounting — the resumed stream skips at least the requested
    batches' worth of *per-shard* batches and stays deterministic."""
    data_dir = _shards(tmp_path, [10, 7, 9])
    a = list(NpzShardDataset(data_dir, batch_size=4, epochs=1,
                             skip_batches=3))
    b = list(NpzShardDataset(data_dir, batch_size=4, epochs=1,
                             skip_batches=3))
    assert len(a) == len(b)
    for (ai, al), (bi, bl) in zip(a, b):
        np.testing.assert_array_equal(ai, bi)
    # No sample before the skip point may reappear: batches 0..2 of
    # the unskipped stream are gone.
    full = list(NpzShardDataset(data_dir, batch_size=4, epochs=1))
    skipped_ids = {float(x) for img, _ in full[:3] for x in img[:, 0]}
    resumed_ids = {float(x) for img, _ in a for x in img[:, 0]}
    assert not (skipped_ids & resumed_ids)
