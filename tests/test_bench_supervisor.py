# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""bench.py supervisor contract: a parseable JSON line ALWAYS lands.

Four consecutive rounds of driver perf records were rc=124 with
``parsed: null`` because the supervisor printed its one diagnostic
line only after every retry + backoff completed — slower than the
driver's kill timer (VERDICT r4, "What's weak" #1).  The fixed
contract under test:

  * a cumulative diagnostic line is printed at supervisor start and
    after EVERY failed attempt (last-line-wins), so an external
    SIGKILL at any moment leaves a parseable record on stdout;
  * BENCH_TOTAL_BUDGET_S caps the whole run — probes, attempts and
    backoffs are clamped to the remaining budget and the final line
    prints before the budget expires.

The probe subprocesses these tests spawn target the axon tunnel
(down or absent in CI), so every attempt fails fast at its clamped
probe cap — exactly the failure mode the driver sees.
"""

import json
import os
import signal
import subprocess
import sys
import time

from tests.conftest import REPO_ROOT

BENCH = os.path.join(REPO_ROOT, "bench.py")


def _env(**extra):
    env = dict(os.environ)
    # Force the supervisor down its failure path deterministically:
    # probes run with the inherited axon,cpu pin (sitecustomize), the
    # tunnel is absent in CI, so each probe hangs or falls back to CPU
    # and is refused. BENCH_PLATFORMS must NOT be set — that would
    # make CPU a legal measurement platform.
    env.pop("BENCH_PLATFORMS", None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _json_lines(out):
    rows = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            rows.append(json.loads(line))
    return rows


def test_total_budget_caps_run_and_final_line_lands():
    # Budget must exceed MIN_USEFUL_S or no attempt starts at all;
    # the override keeps the test fast while the production default
    # (420s) refuses guaranteed-futile budget-tail attempts.
    budget = 150
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=_env(BENCH_ATTEMPTS=6, BENCH_BACKOFF_S=2,
                 BENCH_TOTAL_BUDGET_S=budget,
                 BENCH_MIN_USEFUL_S=90,
                 BENCH_PROBE_TIMEOUT_S=20),
        timeout=budget + 60)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 1
    # The run must respect the budget (plus modest slack for python
    # startup), not the 6-attempt worst case of probes + backoffs.
    assert elapsed < budget + 45, elapsed
    rows = _json_lines(proc.stdout.decode())
    # At least: the at-start emission, one per-failure emission, and
    # the final one.
    assert len(rows) >= 3, rows
    final = rows[-1]
    assert final["value"] == 0.0
    assert final["metric"] == "resnet50_train_throughput"
    assert final["final"] is True
    assert "error" in final and final["error"], final
    # Every emission is the same cumulative shape — any of them is a
    # valid driver record.
    for row in rows:
        assert row["value"] == 0.0
        assert "vs_baseline" in row and "phase" in row


def test_sigkill_mid_run_leaves_parseable_line():
    """Kill the supervisor the moment its first line is out — the
    stdout captured so far must already parse (the driver-kill case)."""
    proc = subprocess.Popen(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=_env(BENCH_ATTEMPTS=6, BENCH_BACKOFF_S=300,
                 BENCH_TOTAL_BUDGET_S=3600,
                 BENCH_PROBE_TIMEOUT_S=240))
    try:
        first = proc.stdout.readline().decode()
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    row = json.loads(first)
    assert row["value"] == 0.0
    assert row["unit"] == "images/sec/chip"
    assert row["final"] is False
