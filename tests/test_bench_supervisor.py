# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""bench.py supervisor contract: a parseable JSON line ALWAYS lands.

Four consecutive rounds of driver perf records were rc=124 with
``parsed: null`` because the supervisor printed its one diagnostic
line only after every retry + backoff completed — slower than the
driver's kill timer (VERDICT r4, "What's weak" #1).  The contract
under test:

  * a cumulative diagnostic line is printed at supervisor start, so
    an external SIGKILL at any moment leaves a parseable record on
    stdout;
  * ONE deadlined backend probe runs BEFORE the retry loop (the
    BENCH_r01-r05 fix): a rig that cannot measure — probe hung, or
    jax fell back to host CPU with no BENCH_PLATFORMS=cpu opt-in —
    resolves to a final ``skipped_unmeasurable`` diagnostic carrying
    the rig fingerprint, in one probe's time instead of three 240s
    hangs with 200s backoffs. perf-check reads such rows as "no
    data", never as a zero-valued regression
    (tests/test_perf_ledger.py).

In CI the probe answers on CPU (conftest pins JAX_PLATFORMS=cpu and
BENCH_PLATFORMS is popped), which is exactly the
unmeasurable-fallback shape the gate must refuse fast.
"""

import json
import os
import signal
import subprocess
import sys
import time

from tests.conftest import REPO_ROOT

BENCH = os.path.join(REPO_ROOT, "bench.py")


def _env(**extra):
    env = dict(os.environ)
    # Force the supervisor down its failure path deterministically:
    # probes run with the inherited axon,cpu pin (sitecustomize), the
    # tunnel is absent in CI, so each probe hangs or falls back to CPU
    # and is refused. BENCH_PLATFORMS must NOT be set — that would
    # make CPU a legal measurement platform.
    env.pop("BENCH_PLATFORMS", None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _json_lines(out):
    rows = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            rows.append(json.loads(line))
    return rows


def test_unmeasurable_rig_resolves_in_one_probe():
    # The BENCH_r01-r05 budget math: the old supervisor burned the
    # whole window on per-attempt probe hangs + backoffs; the gate
    # resolves an unmeasurable rig in ONE probe. The budget below
    # would have allowed an attempt — the gate must answer first.
    budget = 150
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=_env(BENCH_ATTEMPTS=6, BENCH_BACKOFF_S=2,
                 BENCH_TOTAL_BUDGET_S=budget,
                 BENCH_MIN_USEFUL_S=90,
                 BENCH_PROBE_TIMEOUT_S=20),
        timeout=budget + 60)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 1
    # One probe (<= 20s cap) + interpreter startup, not the
    # 6-attempt worst case of probes + backoffs.
    assert elapsed < 90, elapsed
    rows = _json_lines(proc.stdout.decode())
    # The at-start emission plus the final skip record.
    assert len(rows) >= 2, rows
    final = rows[-1]
    assert final["value"] == 0.0
    assert final["metric"] == "resnet50_train_throughput"
    assert final["final"] is True
    assert final["status"] == "skipped_unmeasurable"
    assert "error" in final and final["error"], final
    # The skip record carries the rig fingerprint — the ledger's
    # cross-rig discipline starts at the bench diagnostic itself.
    fp = final["fingerprint"]
    assert fp["platform"] == "cpu" and "jax_version" in fp
    # Every emission is the same cumulative shape — any of them is a
    # valid driver record.
    for row in rows:
        assert row["value"] == 0.0
        assert "vs_baseline" in row and "phase" in row


def test_retry_loop_budget_cap_and_per_failure_emissions():
    """Past the gate, the retry loop's original contract still holds:
    BENCH_PLATFORMS=cpu makes the probe pass (CPU is the requested
    platform), while a 5s attempt timeout kills every child during
    its jax imports — so attempts fail, the supervisor emits a
    cumulative line after EACH failure, and BENCH_TOTAL_BUDGET_S
    stops the loop with the final line printed before an external
    killer would fire (the VERDICT r4 parsed-null pathology)."""
    budget = 60
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=dict(_env(BENCH_ATTEMPTS=6, BENCH_BACKOFF_S=2,
                      BENCH_TOTAL_BUDGET_S=budget,
                      BENCH_MIN_USEFUL_S=20,
                      BENCH_ATTEMPT_TIMEOUT_S=5,
                      BENCH_PROBE_TIMEOUT_S=40),
                 BENCH_PLATFORMS="cpu"),
        timeout=budget + 90)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 1
    # Budget + python-startup slack, not the 6-attempt worst case.
    assert elapsed < budget + 60, elapsed
    rows = _json_lines(proc.stdout.decode())
    # At-start emission, at least one per-failure emission, final.
    assert len(rows) >= 3, rows
    final = rows[-1]
    assert final["final"] is True and final["value"] == 0.0
    assert "rc=" in final["error"], final  # attempts really ran
    for row in rows:
        assert row["value"] == 0.0
        assert "vs_baseline" in row and "phase" in row


def test_sigkill_mid_run_leaves_parseable_line():
    """Kill the supervisor the moment its first line is out — the
    stdout captured so far must already parse (the driver-kill case)."""
    proc = subprocess.Popen(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=_env(BENCH_ATTEMPTS=6, BENCH_BACKOFF_S=300,
                 BENCH_TOTAL_BUDGET_S=3600,
                 BENCH_PROBE_TIMEOUT_S=240))
    try:
        first = proc.stdout.readline().decode()
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    row = json.loads(first)
    assert row["value"] == 0.0
    assert row["unit"] == "images/sec/chip"
    assert row["final"] is False
