# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Every committed measurement artifact must be auditable.

A bare JSON row with a throughput figure is unfalsifiable; the
provenance regime (utils/provenance.py) requires each artifact to
carry WHEN it was taken (generated_utc), AT WHICH commit (git_sha),
and ON WHAT devices. This test walks every committed artifact and
enforces the block uniformly (VERDICT r4 item 6 — previously only
TELEMETRY_PROBE.json was enforced, and ALLOC_BENCH/ATTN_BENCH had
no block at all).

Artifacts stamped after the fact carry a ``retro_stamped`` note
explaining the sourcing; the TPU suite's freshness gate treats
those as stale so they are regenerated cleanly at the next backend
window.
"""

import datetime
import glob
import json
import os
import re
import sys

from tests.conftest import REPO_ROOT

_TOOLS = os.path.join(REPO_ROOT, "tools")
if _TOOLS not in sys.path:
    sys.path.append(_TOOLS)  # append, not insert: tools/ modules
    # must never shadow the package/test import namespace.
from artifact_freshness import is_fresh  # noqa: E402

# Every measurement/probe artifact the repo commits. Missing entries
# fail the test (the record must not silently disappear); extras on
# disk matching the globs are picked up automatically.
REQUIRED = [
    "TPU_BENCH_DEFAULT.json",
    "TPU_BENCH_B256.json",
    "ALLOCATE_ENV_TPU.json",
    "TELEMETRY_PROBE.json",
    "ATTN_BENCH.json",
    "DECODE_BENCH.json",
    "ALLOC_BENCH.json",
    "SERVING_BENCH.json",
]
GLOBS = ["*_BENCH*.json", "ALLOCATE_ENV_TPU.json",
         "TELEMETRY_PROBE.json"]
# Raw sidecars / scratch files the suite writes next to the real
# artifacts; never committed (untracked), never stamped.
EXEMPT = {"SERVING_BENCH_RAW.json"}

SHA_RE = re.compile(r"^[0-9a-f]{40}$")


def _artifacts():
    found = set()
    for pattern in GLOBS:
        for path in glob.glob(os.path.join(REPO_ROOT, pattern)):
            name = os.path.basename(path)
            if name in EXEMPT or name.endswith(".tmp"):
                continue
            found.add(name)
    return found


def test_required_artifacts_exist():
    found = _artifacts()
    missing = [n for n in REQUIRED if n not in found]
    assert not missing, missing


def test_every_artifact_carries_full_provenance():
    problems = []
    for name in sorted(_artifacts()):
        path = os.path.join(REPO_ROOT, name)
        try:
            with open(path) as f:
                d = json.load(f)
        except ValueError as e:
            problems.append(f"{name}: not a JSON object ({e})")
            continue
        prov = (d.get("provenance") or {}) if isinstance(d, dict) \
            else {}
        if not prov:
            problems.append(f"{name}: no provenance block")
            continue
        utc = prov.get("generated_utc")
        try:
            datetime.datetime.fromisoformat(utc)
        except (TypeError, ValueError):
            problems.append(f"{name}: bad generated_utc {utc!r}")
        sha = prov.get("git_sha") or ""
        if not SHA_RE.match(sha):
            problems.append(f"{name}: bad git_sha {sha!r}")
        devices = prov.get("devices")
        if not (isinstance(devices, list) and devices
                and all(isinstance(x, str) and x for x in devices)):
            problems.append(f"{name}: bad devices {devices!r}")
        if "git_dirty" not in prov:
            problems.append(f"{name}: git_dirty missing")
    assert not problems, "\n".join(problems)


def test_freshness_gate_decisions(tmp_path):
    """The suite's skip-if-fresh gate (tools/artifact_freshness.py):
    fresh = auditable + not retro-stamped + younger than the cap."""
    now = 1_700_000_000.0
    utc_new = datetime.datetime.fromtimestamp(
        now - 3600, datetime.timezone.utc).isoformat()
    utc_old = datetime.datetime.fromtimestamp(
        now - 3 * 86400, datetime.timezone.utc).isoformat()
    prov = {"generated_utc": utc_new, "git_sha": "a" * 40,
            "devices": ["TPU v5 lite0"]}

    def write(name, payload):
        p = tmp_path / name
        p.write_text(json.dumps(payload))
        return str(p)

    assert is_fresh(write("fresh.json", {"provenance": prov}), 1,
                    now=now)
    assert not is_fresh(
        write("old.json",
              {"provenance": dict(prov, generated_utc=utc_old)}),
        1, now=now)
    assert is_fresh(
        write("old_wide.json",
              {"provenance": dict(prov, generated_utc=utc_old)}),
        7, now=now)
    assert not is_fresh(
        write("retro.json",
              {"provenance": dict(prov, retro_stamped="note")}),
        1, now=now)
    for missing in ("generated_utc", "git_sha", "devices"):
        broken = dict(prov)
        del broken[missing]
        assert not is_fresh(
            write(f"no_{missing}.json", {"provenance": broken}), 1,
            now=now), missing
    assert not is_fresh(write("bare.json", {"rows": []}), 1, now=now)
    assert not is_fresh(str(tmp_path / "absent.json"), 1, now=now)
    jl = tmp_path / "rows.jsonl"
    jl.write_text('{"a": 1}\n{"a": 2}\n')
    assert not is_fresh(str(jl), 1, now=now)
    # Clock skew: a capture "from the future" is suspect, not fresh.
    future = datetime.datetime.fromtimestamp(
        now + 7200, datetime.timezone.utc).isoformat()
    assert not is_fresh(
        write("future.json",
              {"provenance": dict(prov, generated_utc=future)}),
        1, now=now)


def test_committed_artifact_freshness_matches_expectations():
    """Pin the gate's decisions on the actual committed artifacts:
    retro-stamped records must read STALE (they want a clean rerun)
    whatever their age."""
    for name in ("ATTN_BENCH.json", "DECODE_BENCH.json",
                 "SERVING_BENCH.json"):
        path = os.path.join(REPO_ROOT, name)
        with open(path) as f:
            prov = json.load(f)["provenance"]
        if prov.get("retro_stamped"):
            assert not is_fresh(path, 10_000), name


def _promote(*args):
    import subprocess
    return subprocess.run(
        [sys.executable,
         os.path.join(_TOOLS, "promote_artifact.py"), *args],
        capture_output=True, text=True, timeout=120)


def test_promote_decode_refusals_and_success(tmp_path):
    """The decode promotion (tools/promote_artifact.py) must refuse
    exactly what the round-4 window taught: empty captures, CPU
    fallback rows, and must never touch the committed artifact on
    refusal."""
    out = tmp_path / "DECODE_BENCH.json"
    out.write_text('{"sentinel": true}')
    rows = tmp_path / "rows.jsonl"

    rows.write_text("")
    p = _promote("decode", str(rows), str(out))
    assert p.returncode == 1 and "no rows" in p.stderr
    assert json.loads(out.read_text()) == {"sentinel": True}

    good = {"platform": "tpu", "devices": ["TPU v5 lite0"],
            "decode_tokens_per_sec": 1.0}
    rows.write_text(json.dumps(good) + "\n"
                    + json.dumps(dict(good, platform="cpu")) + "\n")
    p = _promote("decode", str(rows), str(out))
    assert p.returncode == 1 and "not measured on TPU" in p.stderr
    assert json.loads(out.read_text()) == {"sentinel": True}

    # Stricter than the old inline heredoc (which stamped an empty
    # devices list): rows without a devices field are refused, since
    # the stamp would be unauditable.
    rows.write_text(json.dumps(
        {"platform": "tpu", "decode_tokens_per_sec": 1.0}) + "\n")
    p = _promote("decode", str(rows), str(out))
    assert p.returncode == 1 and "no devices" in p.stderr
    assert json.loads(out.read_text()) == {"sentinel": True}

    rows.write_text(json.dumps(good) + "\n" + json.dumps(good) + "\n")
    p = _promote("decode", str(rows), str(out))
    assert p.returncode == 0, p.stderr
    promoted = json.loads(out.read_text())
    assert len(promoted["rows"]) == 2
    assert promoted["provenance"]["devices"] == ["TPU v5 lite0"]
    assert not promoted["provenance"].get("retro_stamped")


def test_promote_serving_refusals_and_success(tmp_path):
    out = tmp_path / "SERVING_BENCH.json"
    out.write_text('{"sentinel": true}')
    raw = tmp_path / "raw.json"
    stats = tmp_path / "stats.json"
    ok_run = {"requests": 300, "errors": 0, "qps": 50.0,
              "p50_ms": 90.0, "p99_ms": 200.0}
    stats.write_text(json.dumps(
        {"platform": "tpu", "devices": ["TPU v5 lite0"]}))

    raw.write_text(json.dumps(
        {"cold": {"error": "load generator produced no result"},
         "warm": ok_run}))
    p = _promote("serving", str(raw), str(stats), str(out))
    assert p.returncode == 1 and "cold run errored" in p.stderr

    raw.write_text(json.dumps(
        {"cold": ok_run,
         "warm": {"requests": 10, "errors": 6}}))
    p = _promote("serving", str(raw), str(stats), str(out))
    assert p.returncode == 1 and "warm summary unusable" in p.stderr

    raw.write_text(json.dumps({"cold": ok_run, "warm": ok_run}))
    stats.write_text(json.dumps({"platform": "cpu", "devices": []}))
    p = _promote("serving", str(raw), str(stats), str(out))
    assert p.returncode == 1 and "want tpu" in p.stderr
    assert json.loads(out.read_text()) == {"sentinel": True}

    stats.write_text(json.dumps(
        {"platform": "tpu", "devices": ["TPU v5 lite0"]}))
    p = _promote("serving", str(raw), str(stats), str(out))
    assert p.returncode == 0, p.stderr
    promoted = json.loads(out.read_text())
    assert promoted["cold_start"]["requests"] == 300
    assert promoted["config"]["readiness_gated"] is True
    assert promoted["provenance"]["devices"] == ["TPU v5 lite0"]
    assert "server_stats" not in promoted  # pre-engine stats shape

    # Engine-era /stats: the occupancy fields ride into the artifact
    # first-class (they replaced the free-text server_stats_note).
    stats.write_text(json.dumps(
        {"platform": "tpu", "devices": ["TPU v5 lite0"],
         "batch_occupancy_avg": 5.21, "slots_active": 3,
         "slots_free": 5, "queue_depth": 2, "engine_steps": 4096,
         "rows_decoded": 21340}))
    p = _promote("serving", str(raw), str(stats), str(out))
    assert p.returncode == 0, p.stderr
    promoted = json.loads(out.read_text())
    assert promoted["server_stats"]["batch_occupancy_avg"] == 5.21
    assert promoted["server_stats"]["slots_active"] == 3
    assert promoted["server_stats"]["queue_depth"] == 2
