# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Table-driven unit tests for pure helpers.

Mirrors the reference's table tests (partition_gpu_test.go:19-63,
util/util_test.go:23-32).
"""

import json

import pytest

from container_engine_accelerators_tpu.plugin.config import (
    TpuConfig,
    parse_tpu_config,
)
from container_engine_accelerators_tpu.plugin.envs import (
    chips_form_box,
    topology_envs,
)
from container_engine_accelerators_tpu.utils import device_name_from_path


@pytest.mark.parametrize("path,name", [
    ("/dev/accel0", "accel0"),
    ("/dev/accel12", "accel12"),
    ("accel3", "accel3"),
])
def test_device_name_from_path(path, name):
    assert device_name_from_path(path) == name


@pytest.mark.parametrize("path", [
    "/dev/nvidia0", "/dev/accel", "/dev/accelx", "/dev/", "/dev/accel-1",
])
def test_device_name_from_path_rejects(path):
    with pytest.raises(ValueError):
        device_name_from_path(path)


def test_parse_config_missing_file(tmp_path):
    assert parse_tpu_config(str(tmp_path / "nope.json")) == TpuConfig()


def test_parse_config_valid(tmp_path):
    p = tmp_path / "tpu_config.json"
    p.write_text(json.dumps({"tpuPartitionSize": "2x2"}))
    assert parse_tpu_config(str(p)).tpu_partition_size == "2x2"


def test_parse_config_invalid_json_soft_fails(tmp_path):
    p = tmp_path / "tpu_config.json"
    p.write_text("{not json")
    assert parse_tpu_config(str(p)) == TpuConfig()


def test_parse_config_wrong_type_soft_fails(tmp_path):
    p = tmp_path / "tpu_config.json"
    p.write_text(json.dumps({"tpuPartitionSize": 4}))
    assert parse_tpu_config(str(p)) == TpuConfig()


def test_chips_form_box():
    assert chips_form_box([(0, 0, 0), (0, 1, 0), (1, 0, 0), (1, 1, 0)])
    assert chips_form_box([(0, 0, 0)])
    assert not chips_form_box([])
    # L-shape: 3 chips of a 2x2 box.
    assert not chips_form_box([(0, 0, 0), (0, 1, 0), (1, 0, 0)])
    # Diagonal: bounding box 2x2 but only 2 chips.
    assert not chips_form_box([(0, 0, 0), (1, 1, 0)])


def test_topology_envs_box():
    envs = topology_envs([0, 1], [(0, 0, 0), (0, 1, 0)])
    assert envs["TPU_VISIBLE_DEVICES"] == "0,1"
    assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"
    assert envs["TPU_PROCESS_BOUNDS"] == "1,1,1"
    assert envs["TPU_SKIP_MDS_QUERY"] == "true"
    assert envs["CLOUD_TPU_TASK_ID"] == "0"


def test_topology_envs_non_box_omits_bounds():
    envs = topology_envs([0, 3], [(0, 0, 0), (1, 1, 0)])
    assert envs["TPU_VISIBLE_DEVICES"] == "0,3"
    assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in envs


def test_topology_envs_worker_override():
    envs = topology_envs([0], [(0, 0, 0)], worker_id=3,
                         worker_hostnames=("w0", "w1", "w2", "w3"))
    assert envs["TPU_WORKER_ID"] == "3"
    assert envs["TPU_WORKER_HOSTNAMES"] == "w0,w1,w2,w3"


def test_manager_multi_host_envs(tmp_path):
    from container_engine_accelerators_tpu.chip import PyChipBackend
    from container_engine_accelerators_tpu.plugin.manager import TpuManager
    dev = tmp_path / "dev"
    state = tmp_path / "state"
    dev.mkdir(); state.mkdir()
    for i in range(4):
        (dev / f"accel{i}").touch()
    (state / "topology").write_text("2x2")
    mgr = TpuManager(dev_dir=str(dev), state_dir=str(state),
                     backend=PyChipBackend(), worker_id=2,
                     worker_hostnames=("w0", "w1", "w2", "w3"))
    mgr.start()
    envs = mgr.allocate_envs(["accel0", "accel1", "accel2", "accel3"])
    assert envs["TPU_WORKER_ID"] == "2"
    assert envs["CLOUD_TPU_TASK_ID"] == "2"
    assert envs["TPU_WORKER_HOSTNAMES"] == "w0,w1,w2,w3"
    assert envs["TPU_PROCESS_BOUNDS"] == "1,1,4"
    assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
