# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""KV-cache decode tests.

The cache path must be *exact* against the full causal forward: for
any generated sequence, re-running the whole sequence densely must
predict the same next token at every step the cache produced — the
strongest property available, and it catches off-by-one cache
index / position-embedding bugs directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import (
    MoETransformerLM,
    TransformerLM,
)
from container_engine_accelerators_tpu.models.decode import (
    beam_search,
    decode,
    greedy_decode,
)

# Tier-1 budget: this module compiles many distinct XLA programs and
# runs minutes on the CI CPU mesh. It only became collectable when the
# shard_map compat shim fixed the jax-version import error, and
# including it would blow the 870s tier-1 cap — so it runs in the full
# lane (`make test` / pytest without `-m "not slow"`) instead.
pytestmark = pytest.mark.slow


V, E, L, H, MAXLEN = 61, 32, 2, 4, 32
B, P, N = 2, 5, 10


@pytest.fixture(scope="module")
def dense_lm():
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, max_seq_len=MAXLEN,
                          dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    return model, params, tokens


def _check_greedy_consistency(model, params, seq, p_len):
    """Every generated token equals the dense forward's argmax at
    the preceding position."""
    outputs = model.apply({"params": params}, seq, train=False)
    logits = outputs[0] if isinstance(outputs, tuple) else outputs
    want = np.asarray(jnp.argmax(logits, axis=-1))
    got = np.asarray(seq)
    for t in range(p_len - 1, seq.shape[1] - 1):
        np.testing.assert_array_equal(got[:, t + 1], want[:, t])


def test_greedy_matches_dense_forward(dense_lm):
    model, params, prompt = dense_lm
    seq = greedy_decode(model, params, prompt, N)
    assert seq.shape == (B, P + N)
    np.testing.assert_array_equal(np.asarray(seq[:, :P]),
                                  np.asarray(prompt))
    _check_greedy_consistency(model, params, seq, P)


def test_greedy_is_deterministic(dense_lm):
    model, params, prompt = dense_lm
    a = greedy_decode(model, params, prompt, N)
    b = greedy_decode(model, params, prompt, N)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sampled_tokens_in_vocab(dense_lm):
    model, params, prompt = dense_lm
    seq = decode(model, params, prompt, N, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    arr = np.asarray(seq[:, P:])
    assert ((arr >= 0) & (arr < V)).all()
    # Different seeds should (overwhelmingly) sample different text.
    seq2 = decode(model, params, prompt, N, temperature=1.0,
                  rng=jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(seq2), np.asarray(seq))


def test_top_k_one_and_tiny_top_p_are_greedy(dense_lm):
    """top_k=1 and a nucleus containing only the argmax both reduce
    sampling to greedy — exact token equality, any seed."""
    model, params, prompt = dense_lm
    want = greedy_decode(model, params, prompt, N)
    for kwargs in ({"top_k": 1}, {"top_p": 1e-6}):
        got = decode(model, params, prompt, N, temperature=1.0,
                     rng=jax.random.PRNGKey(11), **kwargs)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))


def test_top_k_restricts_support(dense_lm):
    """Sampled continuations with top_k must land in each step's
    top-k token set of the dense forward."""
    model, params, prompt = dense_lm
    k = 3
    seq = decode(model, params, prompt, N, temperature=1.0,
                 rng=jax.random.PRNGKey(12), top_k=k)
    outputs = model.apply({"params": params}, seq, train=False)
    logits = outputs[0] if isinstance(outputs, tuple) else outputs
    top = np.asarray(
        jax.lax.top_k(logits, k)[1])  # [B, S, k] token ids
    got = np.asarray(seq)
    for t in range(P - 1, seq.shape[1] - 1):
        for b in range(B):
            assert got[b, t + 1] in top[b, t]


def test_sampling_filter_validation(dense_lm):
    model, params, prompt = dense_lm
    with pytest.raises(ValueError):
        decode(model, params, prompt, N, temperature=1.0, top_k=-1)
    for bad_p in (0.0, 1.5, -0.1):
        with pytest.raises(ValueError):
            decode(model, params, prompt, N, temperature=1.0,
                   top_p=bad_p)


def test_fast_prefill_matches_stepwise(dense_lm):
    """The one-shot-prefill program must produce exactly the
    step-by-step program's greedy text, and zero-token requests keep
    the documented [B, P] shape."""
    model, params, prompt = dense_lm
    fast = decode(model, params, prompt, N, fast_prefill=True)
    slow = decode(model, params, prompt, N, fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))
    assert decode(model, params, prompt, 0).shape == (B, P)
    with pytest.raises(ValueError, match="fast_prefill"):
        decode(model, params, prompt, N, prompt_len=P - 1,
               fast_prefill=True)


def test_per_row_prompt_len_matches_single_row(dense_lm):
    """A batch mixing true prompt lengths (per-row prompt_len vector)
    must generate, per row, exactly what that row produces alone —
    the property cross-request batching in the serving layer relies
    on."""
    model, params, _ = dense_lm
    bucket = 6
    lens = [3, 5]
    rows = []
    for i, n_true in enumerate(lens):
        row = jax.random.randint(jax.random.PRNGKey(10 + i),
                                 (1, n_true), 0, V)
        rows.append(jnp.pad(row, ((0, 0), (0, bucket - n_true))))
    batch = jnp.concatenate(rows, axis=0)
    seq = decode(model, params, batch, N,
                 prompt_len=jnp.asarray(lens, jnp.int32))
    for i, n_true in enumerate(lens):
        alone = decode(model, params, rows[i], N, prompt_len=n_true)
        np.testing.assert_array_equal(
            np.asarray(seq[i, :n_true + N]),
            np.asarray(alone[0, :n_true + N]))


def test_int8_kv_cache_matches_bf16_greedy(dense_lm):
    """int8 KV cache halves cache residency; greedy text on a small
    model must match the full-precision cache (per-row symmetric
    quantization keeps attention logits within argmax tolerance at
    these scales), and the cache leaves must actually be int8."""
    model, params, prompt = dense_lm
    q_model = model.clone(kv_cache_dtype="int8")
    seq_q = greedy_decode(q_model, params, prompt, N)
    seq_f = greedy_decode(model, params, prompt, N)
    np.testing.assert_array_equal(np.asarray(seq_q[:, :P]),
                                  np.asarray(prompt))
    assert seq_q.shape == seq_f.shape
    # DEFLAKED: free-running token agreement is the wrong metric —
    # one near-tie argmax flip makes every later token diverge, so
    # the old >= 0.9 agreement assertion was bimodal (observed
    # spread across prompt seeds 0-7 on this rig: 1.0 for seven
    # seeds, 0.55 for PRNGKey(0) — a flip at the 5th generated
    # token, after which the sequences are unrelated). Instead,
    # teacher-force the SAME text (the f32 greedy output) through
    # both caches and compare each step's echo logprobs: this
    # measures the actual quantization error per position, with no
    # compounding. Observed max |delta| here is ~0.009 nats; 0.05
    # leaves 5x margin while still catching a broken quantizer
    # (zeroed scales or wrong-axis quantization shift logprobs by
    # >> 0.1).
    _, lp_f = decode(model, params, seq_f, 1, return_logprobs=True,
                     fast_prefill=False)
    _, lp_q = decode(q_model, params, seq_f, 1, return_logprobs=True,
                     fast_prefill=False)
    np.testing.assert_allclose(np.asarray(lp_q), np.asarray(lp_f),
                               atol=0.05)

    with pytest.raises(ValueError, match="kv_cache_dtype"):
        greedy_decode(model.clone(kv_cache_dtype="fp8"), params,
                      prompt, N)

    # Inspect the materialized cache collection dtype directly.
    d_model = model.clone(decode=True, kv_cache_dtype="int8")
    variables = d_model.init(jax.random.PRNGKey(2),
                             jnp.zeros((B, MAXLEN), jnp.int32),
                             train=False)
    leaves = jax.tree_util.tree_leaves_with_path(variables["cache"])
    kv = [(p, a) for p, a in leaves
          if "cached_key" in str(p) or "cached_value" in str(p)]
    assert kv and all(a.dtype == jnp.int8 for _, a in kv)
    scales = [a for p, a in leaves if "scale" in str(p)]
    assert scales and all(a.dtype == jnp.float32 for a in scales)


def test_eos_freezes_generated_rows(dense_lm):
    """Once the generated text emits eos_id, the row emits eos_id
    forever; prompt-resident EOS ids don't trigger; tokens before
    the freeze are unchanged."""
    model, params, prompt = dense_lm
    ref = np.asarray(greedy_decode(model, params, prompt, N))
    # Pick row 0's second generated token as its "EOS": generation
    # must match the reference through that token, then freeze.
    eos = int(ref[0, P + 1])
    got = np.asarray(decode(model, params, prompt, N, eos_id=eos))
    np.testing.assert_array_equal(got[0, :P + 2], ref[0, :P + 2])
    assert (got[0, P + 2:] == eos).all()
    # A prompt that CONTAINS the eos id still generates normally.
    prompt_with_eos = jnp.asarray(
        np.concatenate([ref[:, :P - 1],
                        np.full((B, 1), eos, ref.dtype)], axis=1))
    out = np.asarray(decode(model, params, prompt_with_eos, N,
                            eos_id=eos))
    # Row tokens after the prompt are model outputs, not forced eos
    # (unless the model truly emits eos first — check not-all-eos
    # across the batch, which would only happen under the bug).
    assert not (out[:, P:] == eos).all()


def test_eos_per_row_vector(dense_lm):
    """[B] eos vector: -1 disables per row, so a batch can mix
    eos-stopping and free-running rows in one program."""
    model, params, prompt = dense_lm
    ref = np.asarray(greedy_decode(model, params, prompt, N))
    eos_row0 = int(ref[0, P + 1])
    got = np.asarray(decode(
        model, params, prompt, N,
        eos_id=jnp.asarray([eos_row0, -1], jnp.int32)))
    assert (got[0, P + 2:] == eos_row0).all()
    np.testing.assert_array_equal(got[1], ref[1])  # row 1 untouched


def test_beam_one_is_greedy(dense_lm):
    model, params, prompt = dense_lm
    seqs, scores = beam_search(model, params, prompt, N, num_beams=1)
    want = greedy_decode(model, params, prompt, N)
    np.testing.assert_array_equal(np.asarray(seqs[:, 0]),
                                  np.asarray(want))
    assert scores.shape == (B, 1)


def test_beam_scores_sorted_and_consistent(dense_lm):
    """Beams come best-first, and each beam's score equals the sum
    of its tokens' logprobs under the dense forward."""
    model, params, prompt = dense_lm
    k = 3
    seqs, scores = beam_search(model, params, prompt, N, num_beams=k)
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-5).all()  # descending
    for bi in range(B):
        for j in range(k):
            outputs = model.apply({"params": params}, seqs[bi:bi + 1, j],
                                  train=False)
            logits = (outputs[0] if isinstance(outputs, tuple)
                      else outputs)
            lp = jax.nn.log_softmax(
                logits[0].astype(jnp.float32), axis=-1)
            got = np.asarray(seqs[bi, j])
            want = sum(float(lp[t, got[t + 1]])
                       for t in range(P - 1, P + N - 1))
            np.testing.assert_allclose(float(s[bi, j]), want,
                                       rtol=1e-4, atol=1e-4)


def test_beam_wide_equals_exhaustive():
    """With num_beams >= V^N every path survives, so the best beam
    must equal the exhaustive argmax over all continuations."""
    import itertools

    v, n = 5, 2
    model = TransformerLM(vocab_size=v, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=8,
                          dtype=jnp.float32)
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    params = model.init(jax.random.PRNGKey(5), prompt)["params"]
    seqs, scores = beam_search(model, params, prompt, n,
                               num_beams=v ** n)

    best_score, best_path = -np.inf, None
    for path in itertools.product(range(v), repeat=n):
        seq = jnp.asarray([[1, 2, *path]], jnp.int32)
        logits = model.apply({"params": params}, seq, train=False)
        lp = jax.nn.log_softmax(
            np.asarray(logits)[0].astype(np.float32), axis=-1)
        score = sum(lp[t, seq[0, t + 1]] for t in range(1, n + 1))
        if score > best_score:
            best_score, best_path = score, path
    np.testing.assert_array_equal(np.asarray(seqs[0, 0, 2:]),
                                  np.asarray(best_path))
    np.testing.assert_allclose(float(scores[0, 0]), best_score,
                               rtol=1e-4, atol=1e-4)


def test_moe_greedy_matches_dense_forward():
    model = MoETransformerLM(vocab_size=V, embed_dim=E, num_layers=2,
                             num_heads=H, num_experts=4,
                             max_seq_len=MAXLEN, dtype=jnp.float32,
                             capacity_factor=4.0)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(3), tokens)["params"]
    seq = greedy_decode(model, params, tokens, N)
    assert seq.shape == (B, P + N)
    _check_greedy_consistency(model, params, seq, P)


def test_gqa_decode_matches_dense_forward():
    """Grouped-query attention (num_kv_heads < num_heads): greedy
    decode must stay argmax-consistent with the model's own dense
    forward, the KV cache must actually shrink to Hkv heads, and the
    one-shot prefill path must agree with stepwise decode."""
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, num_kv_heads=2,
                          max_seq_len=MAXLEN, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    # GQA uses split q / kv projections instead of the fused qkv.
    attn0 = params["block0"]["attn"]
    assert "q" in attn0 and "kv" in attn0 and "qkv" not in attn0

    seq = greedy_decode(model, params, tokens, N)
    _check_greedy_consistency(model, params, seq, P)

    from container_engine_accelerators_tpu.models.decode import (
        init_cache,
    )
    _, cache = init_cache(model, B, MAXLEN)
    assert cache["block0"]["attn"]["cached_key"].shape == (
        B, MAXLEN, 2, E // H)

    fast = decode(model, params, tokens, N, fast_prefill=True)
    step = decode(model, params, tokens, N, fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(step))


def test_gqa_int8_cache_matches_f32_greedy():
    model_kwargs = dict(vocab_size=V, embed_dim=E, num_layers=L,
                        num_heads=H, num_kv_heads=2,
                        max_seq_len=MAXLEN, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (B, P), 0, V)
    base = TransformerLM(**model_kwargs)
    params = base.init(jax.random.PRNGKey(1), tokens)["params"]
    want = greedy_decode(base, params, tokens, N)
    got = greedy_decode(TransformerLM(kv_cache_dtype="int8",
                                      **model_kwargs),
                        params, tokens, N)
    # int8 quantization perturbs logits; greedy picks usually agree
    # at these sizes — require exact agreement on the prompt + first
    # tokens and full shape agreement overall.
    assert got.shape == want.shape
    np.testing.assert_array_equal(np.asarray(got[:, :P + 1]),
                                  np.asarray(want[:, :P + 1]))


def test_gqa_rejects_indivisible_heads():
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=1,
                          num_heads=4, num_kv_heads=3,
                          max_seq_len=MAXLEN, dtype=jnp.float32)
    with pytest.raises(ValueError, match="must divide"):
        model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 4), jnp.int32))


def test_rope_decode_matches_dense_forward():
    """RoPE position encoding: decode must stay argmax-consistent
    with the dense forward (the cache holds rotated keys, so the
    step is an ordinary dot product), one-shot prefill must agree
    with stepwise, and there must be no learned position table."""
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, pos_embedding="rope",
                          max_seq_len=MAXLEN, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    assert "pos_embed" not in params

    seq = greedy_decode(model, params, tokens, N)
    _check_greedy_consistency(model, params, seq, P)

    fast = decode(model, params, tokens, N, fast_prefill=True)
    step = decode(model, params, tokens, N, fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(step))


def test_rope_relative_shift_property():
    """RoPE scores depend only on relative position: rotating q/k at
    positions p and p + delta gives identical attention weights."""
    from container_engine_accelerators_tpu.models.transformer import (
        apply_rope,
    )

    ks = jax.random.split(jax.random.PRNGKey(5), 2)
    q = jax.random.normal(ks[0], (1, 6, 2, 8))
    k = jax.random.normal(ks[1], (1, 6, 2, 8))

    def scores(offset):
        pos = offset + jnp.arange(6, dtype=jnp.int32)
        qr, kr = apply_rope(q, pos), apply_rope(k, pos)
        return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

    np.testing.assert_allclose(np.asarray(scores(0)),
                               np.asarray(scores(11)),
                               rtol=1e-5, atol=1e-5)


def test_rope_gqa_int8_compose():
    """All three LM options together: rope + GQA + int8 cache."""
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, num_kv_heads=2,
                          pos_embedding="rope", kv_cache_dtype="int8",
                          max_seq_len=MAXLEN, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    seq = greedy_decode(model, params, tokens, N)
    assert seq.shape == (B, P + N)
    assert np.asarray(seq).min() >= 0 and np.asarray(seq).max() < V


def test_bad_pos_embedding_rejected():
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=1,
                          num_heads=2, pos_embedding="alibi",
                          max_seq_len=MAXLEN)
    with pytest.raises(ValueError, match="pos_embedding"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def test_sliding_window_decode_matches_dense_forward():
    """attention_window: decode (windowed cache mask) must stay
    argmax-consistent with the model's own dense forward (windowed
    flash), and fast prefill must agree with stepwise."""
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, attention_window=6,
                          max_seq_len=MAXLEN, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    seq = greedy_decode(model, params, tokens, N)
    _check_greedy_consistency(model, params, seq, P)
    fast = decode(model, params, tokens, N, fast_prefill=True)
    step = decode(model, params, tokens, N, fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(step))


def test_repetition_penalty(dense_lm):
    """penalty=1.0 is exactly the unpenalized program; a strong
    penalty changes greedy output and suppresses repeats; fast
    prefill matches stepwise with the penalty on."""
    model, params, prompt = dense_lm
    base = decode(model, params, prompt, N)
    neutral = decode(model, params, prompt, N, repetition_penalty=1.0)
    np.testing.assert_array_equal(np.asarray(base),
                                  np.asarray(neutral))

    pen = decode(model, params, prompt, N, repetition_penalty=1e6)
    assert not np.array_equal(np.asarray(pen), np.asarray(base))
    # With an effectively infinite penalty and N + P << V, greedy
    # should never emit the same token twice in a row.
    gen = np.asarray(pen)[:, P:]
    assert (gen[:, 1:] != gen[:, :-1]).all()

    fast = decode(model, params, prompt, N, repetition_penalty=1e6,
                  fast_prefill=True)
    step = decode(model, params, prompt, N, repetition_penalty=1e6,
                  fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(step))

    with pytest.raises(ValueError, match="must be > 0"):
        decode(model, params, prompt, N, repetition_penalty=0.0)


def test_beam_search_composes_with_gqa_rope():
    """Beam search shares the cache machinery; it must run unchanged
    on a GQA + RoPE model and return valid, prompt-prefixed beams."""
    from container_engine_accelerators_tpu.models.decode import (
        beam_search,
    )

    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, num_kv_heads=2,
                          pos_embedding="rope", max_seq_len=MAXLEN,
                          dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(8), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    seqs, scores = beam_search(model, params, tokens, 6, num_beams=3)
    assert seqs.shape == (B, 3, P + 6)
    np.testing.assert_array_equal(
        np.asarray(seqs[:, 0, :P]), np.asarray(tokens))
    s = np.asarray(scores)
    assert (s[:, :-1] >= s[:, 1:] - 1e-5).all()  # sorted best-first


def test_min_p_filter(dense_lm):
    """min_p close to 1 forces near-greedy sampling; min_p=0.0 is
    exactly the unfiltered program; validation rejects bad values."""
    model, params, prompt = dense_lm
    greedy = decode(model, params, prompt, N)
    near = decode(model, params, prompt, N, temperature=0.05,
                  min_p=0.97, rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(near), np.asarray(greedy))

    a = decode(model, params, prompt, N, temperature=1.0,
               rng=jax.random.PRNGKey(5))
    b = decode(model, params, prompt, N, temperature=1.0, min_p=0.0,
               rng=jax.random.PRNGKey(5))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with pytest.raises(ValueError, match="min_p"):
        decode(model, params, prompt, N, temperature=1.0, min_p=1.0)


def test_windowed_ring_cache_is_window_sized_and_wraps_exactly():
    """Sliding-window decode keeps an O(window) ring cache (slot =
    position % window), and stays argmax-consistent with the dense
    windowed forward even after generation has wrapped the ring
    several times — the eviction path, where a stale slot must never
    pass the band mask."""
    from container_engine_accelerators_tpu.models.decode import (
        init_cache,
    )

    W, n_new = 6, 20  # wraps the 6-slot ring 3+ times
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, attention_window=W,
                          max_seq_len=MAXLEN, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(9), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]

    _, cache = init_cache(model, B, P + n_new)
    attn = cache["block0"]["attn"]
    assert attn["cached_key"].shape == (B, W, H, E // H)
    assert attn["slot_pos"].shape == (B, W)

    seq = greedy_decode(model, params, tokens, n_new)
    _check_greedy_consistency(model, params, seq, P)


def test_windowed_ring_cache_composes_gqa_rope_int8():
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, num_kv_heads=2,
                          pos_embedding="rope", kv_cache_dtype="int8",
                          attention_window=6, max_seq_len=MAXLEN,
                          dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(10), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    seq = greedy_decode(model, params, tokens, 16)
    assert seq.shape == (B, P + 16)
    got = np.asarray(seq)
    assert got.min() >= 0 and got.max() < V
    fast = decode(model, params, tokens, 16, fast_prefill=True)
    step = decode(model, params, tokens, 16, fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(step))


def test_windowed_ring_prefill_longer_than_window():
    """Prompt longer than the window: one-shot prefill keeps only
    the last W entries (static wrap split), and decode remains
    argmax-consistent with the dense windowed forward."""
    W = 4  # < P=5, so the prefill write wraps
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, attention_window=W,
                          max_seq_len=MAXLEN, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(12), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    fast = decode(model, params, tokens, N, fast_prefill=True)
    step = decode(model, params, tokens, N, fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(step))
    _check_greedy_consistency(model, params, fast, P)


def test_return_logprobs(dense_lm):
    """Logprob entries must equal the dense forward's log-softmax at
    the emitted tokens — prompt (echo) and generated alike — and the
    fast-prefill path must match stepwise."""
    model, params, prompt = dense_lm
    seq, lp = decode(model, params, prompt, N, return_logprobs=True)
    assert lp.shape == (B, P + N) and lp.dtype == jnp.float32

    logits = model.apply({"params": params}, seq, train=False)
    want = np.asarray(jax.nn.log_softmax(
        logits.astype(jnp.float32), -1))
    got_seq = np.asarray(seq)
    got_lp = np.asarray(lp)
    assert (got_lp[:, 0] == 0.0).all()
    for t in range(1, P + N):
        ref = want[np.arange(B), t - 1, got_seq[:, t]]
        np.testing.assert_allclose(got_lp[:, t], ref, rtol=1e-4,
                                   atol=1e-4)

    seq2, lp2 = decode(model, params, prompt, N, return_logprobs=True,
                       fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(seq2), got_seq)
    np.testing.assert_allclose(np.asarray(lp2), got_lp, rtol=1e-4,
                               atol=1e-4)


def test_decode_option_fuzz():
    """Random combinations of every sampling/penalty/filter option on
    a GQA+RoPE model: outputs must always be valid vocab ids with the
    prompt preserved, logprob arrays finite-or-zero and aligned —
    the 'options compose' invariant no pairwise test covers."""
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, num_kv_heads=2,
                          pos_embedding="rope", max_seq_len=MAXLEN,
                          dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(20), (B, P), 0, V)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    rng = np.random.RandomState(0)
    for trial in range(8):
        sample = bool(rng.rand() < 0.7)
        kwargs = dict(
            temperature=float(rng.uniform(0.3, 1.5)) if sample else 0.0,
            top_k=int(rng.choice([0, 4, 8])) if sample else 0,
            top_p=float(rng.choice([1.0, 0.9])) if sample else 1.0,
            min_p=float(rng.choice([0.0, 0.05])) if sample else 0.0,
            repetition_penalty=float(rng.choice([1.0, 1.3])),
            eos_id=int(rng.choice([-1, 3])),
            return_logprobs=bool(rng.rand() < 0.5),
            rng=jax.random.PRNGKey(trial),
        )
        if kwargs["eos_id"] < 0:
            kwargs.pop("eos_id")
        out = decode(model, params, tokens, 6, **kwargs)
        if kwargs["return_logprobs"]:
            seq, lp = out
            got_lp = np.asarray(lp)
            assert got_lp.shape == (B, P + 6)
            assert np.isfinite(got_lp).all()
            assert (got_lp[:, 0] == 0.0).all()
        else:
            seq = out
        got = np.asarray(seq)
        assert got.shape == (B, P + 6)
        np.testing.assert_array_equal(got[:, :P], np.asarray(tokens))
        assert got.min() >= 0 and got.max() < V, (trial, kwargs)


def test_mask_min_p_zero_row_exact_in_mixed_batch():
    """A min_p=0.0 row in a mixed batch must be EXACTLY transparent:
    the old 1e-38 clamp still masked tokens with probability below
    1e-38 * p_max, so the same row behaved differently batched with a
    min_p>0 row than in an all-zero batch (ADVICE r2)."""
    from container_engine_accelerators_tpu.models.decode import (
        _mask_min_p,
    )

    logits = jnp.array([[0.0, -200.0, -5.0],
                        [0.0, -200.0, -5.0]], jnp.float32)
    out = _mask_min_p(logits, jnp.array([0.5, 0.0], jnp.float32))
    # Row 0 (min_p=0.5): both sub-threshold tokens masked.
    assert np.isneginf(np.asarray(out)[0, 1])
    assert np.isneginf(np.asarray(out)[0, 2])
    # Row 1 (min_p=0.0): exact no-op, even for p ~ e^-200 < 1e-38.
    np.testing.assert_array_equal(np.asarray(out)[1],
                                  np.asarray(logits)[1])


def test_prefix_cache_greedy_equals_full_decode(dense_lm):
    """decode_with_prefix on a shared prefix is token-for-token the
    full decode of (prefix + suffix) — the prefill-once fan-out path
    changes where FLOPs are spent, never what is generated."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model, params, _ = dense_lm
    prefix = jax.random.randint(jax.random.PRNGKey(20), (1, 6), 0, V)
    suffixes = jax.random.randint(jax.random.PRNGKey(21), (3, 4), 0, V)
    state = prefill_prefix(model, params, prefix,
                           max_total_len=6 + 4 + N)
    got = decode_with_prefix(model, params, state, suffixes, N)
    assert got.shape == (3, 4 + N)
    full = decode(
        model, params,
        jnp.concatenate([jnp.broadcast_to(prefix, (3, 6)), suffixes],
                        axis=1), N)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(full)[:, 6:])


def test_prefix_cache_multi_row_prefix_fan_out(dense_lm):
    """A [2]-row prefix fans out to 4 request rows: row i continues
    prefix row i // 2."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model, params, _ = dense_lm
    prefix = jax.random.randint(jax.random.PRNGKey(22), (2, 5), 0, V)
    suffixes = jax.random.randint(jax.random.PRNGKey(23), (4, 3), 0, V)
    state = prefill_prefix(model, params, prefix,
                           max_total_len=5 + 3 + N)
    got = decode_with_prefix(model, params, state, suffixes, N)
    expanded = jnp.repeat(prefix, 2, axis=0)
    full = decode(model, params,
                  jnp.concatenate([expanded, suffixes], axis=1), N)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(full)[:, 5:])


def test_prefix_cache_eos_and_ragged_suffix(dense_lm):
    """EOS freezing and per-row ragged suffix lengths compose with
    the prefix path exactly as with full decode."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model, params, _ = dense_lm
    prefix = jax.random.randint(jax.random.PRNGKey(24), (1, 4), 0, V)
    suffixes = jax.random.randint(jax.random.PRNGKey(25), (2, 4), 0, V)
    p_len = jnp.array([3, 4], jnp.int32)
    eos = 7
    state = prefill_prefix(model, params, prefix,
                           max_total_len=4 + 4 + N)
    got = decode_with_prefix(model, params, state, suffixes, N,
                             prompt_len=p_len, eos_id=eos)
    full = decode(
        model, params,
        jnp.concatenate([jnp.broadcast_to(prefix, (2, 4)), suffixes],
                        axis=1), N, prompt_len=4 + p_len, eos_id=eos)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(full)[:, 4:])


def test_prefix_cache_sampling_stays_in_vocab_and_t0_limit(dense_lm):
    """Sampling through the prefix path: tokens stay in-vocab, and
    top_k=1 (support of one) reproduces greedy regardless of rng."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model, params, _ = dense_lm
    prefix = jax.random.randint(jax.random.PRNGKey(26), (1, 5), 0, V)
    suffixes = jax.random.randint(jax.random.PRNGKey(27), (2, 3), 0, V)
    state = prefill_prefix(model, params, prefix,
                           max_total_len=5 + 3 + N)
    sampled = decode_with_prefix(model, params, state, suffixes, N,
                                 temperature=0.9,
                                 rng=jax.random.PRNGKey(28))
    assert ((np.asarray(sampled) >= 0)
            & (np.asarray(sampled) < V)).all()
    k1 = decode_with_prefix(model, params, state, suffixes, N,
                            temperature=0.7, top_k=1,
                            rng=jax.random.PRNGKey(29))
    greedy = decode_with_prefix(model, params, state, suffixes, N)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))


def test_prefix_cache_validation(dense_lm):
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model, params, _ = dense_lm
    prefix = jnp.zeros((2, 4), jnp.int32)
    with pytest.raises(ValueError, match="no room"):
        prefill_prefix(model, params, prefix, max_total_len=4)
    state = prefill_prefix(model, params, prefix, max_total_len=12)
    with pytest.raises(ValueError, match="multiple"):
        decode_with_prefix(model, params, state,
                           jnp.zeros((3, 2), jnp.int32), 2)
    with pytest.raises(ValueError, match="overflows"):
        decode_with_prefix(model, params, state,
                           jnp.zeros((2, 4), jnp.int32), 8)


def test_prefix_cache_sliding_window_model():
    """The prefix path composes with a sliding-window ring cache:
    capacity comes from the state's max_total_len, not the W-sized
    buffer, and outputs still match full decode token-for-token."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    w = 8
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, max_seq_len=MAXLEN,
                          attention_window=w, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(30), (1, 6), 0, V)
    params = model.init(jax.random.PRNGKey(31), tokens)["params"]
    suffixes = jax.random.randint(jax.random.PRNGKey(32), (2, 4), 0, V)
    # prefix 6 + suffix 4 + N 10 = 20 total > window 8: the ring
    # cache wraps during generation.
    state = prefill_prefix(model, params, tokens,
                           max_total_len=6 + 4 + N)
    got = decode_with_prefix(model, params, state, suffixes, N)
    full = decode(
        model, params,
        jnp.concatenate([jnp.broadcast_to(tokens, (2, 6)), suffixes],
                        axis=1), N)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(full)[:, 6:])


def test_prefix_cache_negative_top_k_rejected(dense_lm):
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model, params, _ = dense_lm
    state = prefill_prefix(model, params,
                           jnp.zeros((1, 4), jnp.int32),
                           max_total_len=20)
    with pytest.raises(ValueError, match="top_k"):
        decode_with_prefix(model, params, state,
                           jnp.zeros((1, 2), jnp.int32), 2,
                           temperature=0.9, top_k=-1)


def test_prefix_cache_fast_suffix_prefill_matches_stepwise(dense_lm):
    """The one-chunk suffix prefill (mid-cache chunk apply) equals
    the stepwise scan token-for-token, greedy and top_k=1 sampling
    alike — and both equal full decode."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model, params, _ = dense_lm
    prefix = jax.random.randint(jax.random.PRNGKey(33), (1, 6), 0, V)
    suffixes = jax.random.randint(jax.random.PRNGKey(34), (3, 5), 0, V)
    state = prefill_prefix(model, params, prefix,
                           max_total_len=6 + 5 + N)
    fast = decode_with_prefix(model, params, state, suffixes, N,
                              fast_prefill=True)
    slow = decode_with_prefix(model, params, state, suffixes, N,
                              fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))
    # top_k=1 sampling (support of one -> deterministic regardless
    # of each path's different rng stream) exercises the sampling
    # branch of the fast chunk pick.
    fast_s = decode_with_prefix(model, params, state, suffixes, N,
                                temperature=0.7, top_k=1,
                                fast_prefill=True)
    np.testing.assert_array_equal(np.asarray(fast_s),
                                  np.asarray(fast))
    full = decode(
        model, params,
        jnp.concatenate([jnp.broadcast_to(prefix, (3, 6)), suffixes],
                        axis=1), N)
    np.testing.assert_array_equal(np.asarray(fast),
                                  np.asarray(full)[:, 6:])
    with pytest.raises(ValueError, match="fast_prefill"):
        decode_with_prefix(model, params, state, suffixes, N,
                           prompt_len=jnp.array([4, 5, 5]),
                           fast_prefill=True)


def test_stream_decode_greedy_equals_one_shot(dense_lm):
    """Chunked streaming generation is token-for-token the one-shot
    greedy decode — chunk boundaries change when tokens arrive,
    never what they are."""
    from container_engine_accelerators_tpu.models.decode import (
        stream_decode,
    )

    model, params, prompt = dense_lm
    want = np.asarray(greedy_decode(model, params, prompt, N))
    for chunk in (1, 3, N):
        blocks = list(stream_decode(model, params, prompt, N,
                                    chunk=chunk))
        got = np.concatenate(blocks, axis=1)
        assert got.shape == (B, N)
        np.testing.assert_array_equal(got, want[:, P:])


def test_stream_decode_single_token_prompt(dense_lm):
    from container_engine_accelerators_tpu.models.decode import (
        stream_decode,
    )

    model, params, _ = dense_lm
    prompt = jnp.array([[7], [9]], jnp.int32)
    want = np.asarray(greedy_decode(model, params, prompt, 6))
    got = np.concatenate(
        list(stream_decode(model, params, prompt, 6, chunk=2)),
        axis=1)
    np.testing.assert_array_equal(got, want[:, 1:])


def test_stream_decode_eos_freezes_and_stops(dense_lm):
    """A row that emits EOS stays frozen in every later block, and
    the stream ends early once all rows finish."""
    from container_engine_accelerators_tpu.models.decode import (
        stream_decode,
    )

    model, params, prompt = dense_lm
    full = np.asarray(greedy_decode(model, params, prompt, N))
    # Use the token the model actually generates first as row 0's
    # EOS, so the freeze provably triggers mid-stream.
    eos = int(full[0, P])
    blocks = list(stream_decode(model, params, prompt, N, chunk=2,
                                eos_id=eos))
    got = np.concatenate(blocks, axis=1)
    row0 = got[0]
    first = int(np.argmax(row0 == eos))
    assert (row0[first:] == eos).all()  # frozen after first EOS
    # Single-row stream whose first generated token IS the EOS: the
    # early-stop must end the stream after the first block instead
    # of emitting all N tokens.
    one = prompt[:1]
    blocks1 = list(stream_decode(model, params, one, N, chunk=2,
                                 eos_id=eos))
    total1 = sum(b.shape[1] for b in blocks1)
    assert total1 < N  # genuinely stopped early
    assert int(blocks1[0][0, 0]) == eos


def test_stream_decode_sampling_in_vocab(dense_lm):
    from container_engine_accelerators_tpu.models.decode import (
        stream_decode,
    )

    model, params, prompt = dense_lm
    got = np.concatenate(
        list(stream_decode(model, params, prompt, 8, chunk=3,
                           temperature=0.9, top_k=8,
                           rng=jax.random.PRNGKey(5))),
        axis=1)
    assert got.shape == (B, 8)
    assert ((got >= 0) & (got < V)).all()


def test_prefix_cache_composes_int8_gqa_rope():
    """The prefix path on a GQA + rope + int8-cache model (the
    serving-economy composition): greedy equality with full decode,
    incl. the int8 scale leaves riding the cache fan-out."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=4, num_kv_heads=2,
                          pos_embedding="rope", kv_cache_dtype="int8",
                          max_seq_len=MAXLEN, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(40), (1, 6), 0, V)
    params = model.init(jax.random.PRNGKey(41), tokens)["params"]
    suffixes = jax.random.randint(jax.random.PRNGKey(42), (2, 4), 0, V)
    state = prefill_prefix(model, params, tokens,
                           max_total_len=6 + 4 + N)
    got = decode_with_prefix(model, params, state, suffixes, N)
    full = decode(
        model, params,
        jnp.concatenate([jnp.broadcast_to(tokens, (2, 6)), suffixes],
                        axis=1), N)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(full)[:, 6:])


def test_beam_eos_equals_exhaustive_truncated_scoring():
    """With eos_id set and num_beams >= V^N, the best beam equals
    the exhaustive argmax where a path's score is the sum of
    logprobs through its FIRST eos (finished-hypothesis semantics),
    and the winning row pads with eos after finishing."""
    import itertools

    v, n, eos = 5, 3, 2
    model = TransformerLM(vocab_size=v, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=8,
                          dtype=jnp.float32)
    prompt = jnp.asarray([[1, 3]], jnp.int32)
    params = model.init(jax.random.PRNGKey(6), prompt)["params"]
    seqs, scores = beam_search(model, params, prompt, n,
                               num_beams=v ** n, eos_id=eos)

    def truncated_score(path):
        # Model logprobs along the path, stopping at the first eos;
        # positions after it contribute nothing (the in-beam freeze).
        seq = jnp.asarray([[1, 3, *path]], jnp.int32)
        logits = model.apply({"params": params}, seq, train=False)
        lp = jax.nn.log_softmax(
            np.asarray(logits)[0].astype(np.float32), axis=-1)
        score = 0.0
        for t in range(1, n + 1):
            score += lp[t, seq[0, t + 1]]
            if int(seq[0, t + 1]) == eos:
                break
        return score

    best_score, best_path = -np.inf, None
    seen = set()
    for path in itertools.product(range(v), repeat=n):
        # Canonicalize: tokens after the first eos are frozen to eos
        # in the beam representation, so distinct raw paths that
        # share a truncated form are ONE hypothesis.
        canon = []
        done = False
        for tok in path:
            canon.append(eos if done else tok)
            done = done or tok == eos
        canon = tuple(canon)
        if canon in seen:
            continue
        seen.add(canon)
        score = truncated_score(canon)
        if score > best_score:
            best_score, best_path = score, canon
    np.testing.assert_array_equal(np.asarray(seqs[0, 0, 2:]),
                                  np.asarray(best_path))
    np.testing.assert_allclose(float(scores[0, 0]), best_score,
                               rtol=1e-4, atol=1e-4)
    # A finished winner stays frozen: everything after its first eos
    # is eos.
    row = np.asarray(seqs[0, 0, 2:])
    if eos in row:
        first = int(np.argmax(row == eos))
        assert (row[first:] == eos).all()


def test_beam_eos_off_unchanged(dense_lm):
    """eos_id=None reproduces the exact pre-EOS beam behavior."""
    model, params, prompt = dense_lm
    a, sa = beam_search(model, params, prompt, 6, num_beams=3)
    b_, sb = beam_search(model, params, prompt, 6, num_beams=3,
                         eos_id=None)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))


def test_beam_eos_vector_rejected(dense_lm):
    model, params, prompt = dense_lm
    with pytest.raises(ValueError, match="scalar"):
        beam_search(model, params, prompt, 4, num_beams=2,
                    eos_id=jnp.array([2, 2]))


@pytest.mark.parametrize("seed,n", [
    (6, 3),
    # seed 9 / n=2: the best penalized path emits EOS exactly at the
    # final generated token — the case where a one-step-late penalty
    # would rank it raw (review find).
    (9, 2),
])
def test_beam_length_penalty_equals_exhaustive(seed, n):
    """With length_penalty alpha and full-width beams, the best beam
    equals the exhaustive argmax where every hypothesis ending in
    eos ranks by score / ((5+len)/6)^alpha (len through first eos)
    and live ones rank raw — the GNMT/t5x convention."""
    import itertools

    v, eos, alpha = 5, 2, 1.4
    model = TransformerLM(vocab_size=v, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=8,
                          dtype=jnp.float32)
    prompt = jnp.asarray([[1, 3]], jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), prompt)["params"]
    seqs, scores = beam_search(model, params, prompt, n,
                               num_beams=v ** n, eos_id=eos,
                               length_penalty=alpha)

    def path_eff(canon):
        seq = jnp.asarray([[1, 3, *canon]], jnp.int32)
        logits = model.apply({"params": params}, seq, train=False)
        lp_ = jax.nn.log_softmax(
            np.asarray(logits)[0].astype(np.float32), axis=-1)
        raw, length, finished = 0.0, 0, False
        for t in range(1, n + 1):
            raw += lp_[t, seq[0, t + 1]]
            length += 1
            if int(seq[0, t + 1]) == eos:
                finished = True
                break
        if finished:
            return raw / (((5.0 + length) / 6.0) ** alpha)
        return raw

    best_eff, best_path = -np.inf, None
    seen = set()
    for path in itertools.product(range(v), repeat=n):
        canon, done = [], False
        for tok in path:
            canon.append(eos if done else tok)
            done = done or tok == eos
        canon = tuple(canon)
        if canon in seen:
            continue
        seen.add(canon)
        eff = path_eff(canon)
        if eff > best_eff:
            best_eff, best_path = eff, canon
    np.testing.assert_array_equal(np.asarray(seqs[0, 0, 2:]),
                                  np.asarray(best_path))
    np.testing.assert_allclose(float(scores[0, 0]), best_eff,
                               rtol=1e-4, atol=1e-4)
    # alpha=0 via the use_lp gate is byte-identical to the plain EOS
    # path.
    a0, s0 = beam_search(model, params, prompt, n, num_beams=4,
                         eos_id=eos)
    a1, s1 = beam_search(model, params, prompt, n, num_beams=4,
                         eos_id=eos, length_penalty=0.0)
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
    with pytest.raises(ValueError, match="requires eos_id"):
        beam_search(model, params, prompt, n, num_beams=2,
                    length_penalty=0.5)


def test_map_batch_leaves_structure_keyed():
    """Cache batch transforms key on the tree's structural contract
    (ndim >= 2 == batch-major), not leading-dim size coincidences: a
    non-batch leaf whose length happens to equal the batch must pass
    through untouched, and scalars are always shared (ADVICE r4)."""
    from container_engine_accelerators_tpu.models.decode import (
        _map_batch_leaves,
    )

    tree = {
        "cached_key": jnp.zeros((2, 4, 3, 5)),
        "slot_pos": jnp.zeros((2, 7), jnp.int32),
        "cache_index": jnp.zeros((), jnp.int32),
        # 1-D, length == batch: the old shape-coincidence rule would
        # have repeated this.
        "not_a_batch_leaf": jnp.zeros((2,)),
    }
    out = _map_batch_leaves(lambda a: jnp.repeat(a, 3, axis=0), tree)
    assert out["cached_key"].shape == (6, 4, 3, 5)
    assert out["slot_pos"].shape == (6, 7)
    assert out["cache_index"].shape == ()
    assert out["not_a_batch_leaf"].shape == (2,)


def test_prefix_cache_windowed_fast_prefill_with_chunk_slack():
    """Chunked suffix prefill on a sliding-window model: a prefix
    state allocated with chunk_slack >= suffix width runs the suffix
    as ONE mid-cache ring chunk (scatter write) and matches the
    stepwise path and full decode token-for-token; an undersized
    state refuses fast_prefill=True."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    w = 8
    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, max_seq_len=MAXLEN,
                          attention_window=w, dtype=jnp.float32)
    prefix = jax.random.randint(jax.random.PRNGKey(40), (1, 6), 0, V)
    params = model.init(jax.random.PRNGKey(41), prefix)["params"]
    suffixes = jax.random.randint(jax.random.PRNGKey(42), (2, 5), 0, V)
    # 6 + 5 + 10 = 21 total > window 8: the ring wraps during both
    # the suffix chunk and generation.
    state = prefill_prefix(model, params, prefix,
                           max_total_len=6 + 5 + N, chunk_slack=5)
    fast = decode_with_prefix(model, params, state, suffixes, N,
                              fast_prefill=True)
    slow = decode_with_prefix(model, params, state, suffixes, N,
                              fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))
    full = decode(
        model, params,
        jnp.concatenate([jnp.broadcast_to(prefix, (2, 6)), suffixes],
                        axis=1), N)
    np.testing.assert_array_equal(np.asarray(fast),
                                  np.asarray(full)[:, 6:])
    # Without slack the ring cannot hold window + suffix: explicit
    # fast_prefill must refuse (the default silently goes stepwise).
    bare = prefill_prefix(model, params, prefix,
                          max_total_len=6 + 5 + N)
    with pytest.raises(ValueError, match="ring"):
        decode_with_prefix(model, params, bare, suffixes, N,
                           fast_prefill=True)
    got = decode_with_prefix(model, params, bare, suffixes, N)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(fast))


def test_prefix_cache_windowed_fast_prefill_no_wrap_needs_no_slack():
    """A ring that never wraps (max_total_len <= window) has full
    capacity by construction, so chunked suffix prefill works on a
    slack-free state."""
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, max_seq_len=MAXLEN,
                          attention_window=24, dtype=jnp.float32)
    prefix = jax.random.randint(jax.random.PRNGKey(43), (1, 4), 0, V)
    params = model.init(jax.random.PRNGKey(44), prefix)["params"]
    suffixes = jax.random.randint(jax.random.PRNGKey(45), (2, 4), 0, V)
    state = prefill_prefix(model, params, prefix,
                           max_total_len=4 + 4 + 8)  # 16 <= 24
    fast = decode_with_prefix(model, params, state, suffixes, 8,
                              fast_prefill=True)
    slow = decode_with_prefix(model, params, state, suffixes, 8,
                              fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_prefix_cache_windowed_chunk_slack_composes_int8_gqa_rope():
    from container_engine_accelerators_tpu.models.decode import (
        decode_with_prefix,
        prefill_prefix,
    )

    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=L,
                          num_heads=H, max_seq_len=MAXLEN,
                          attention_window=8, num_kv_heads=2,
                          pos_embedding="rope", kv_cache_dtype="int8",
                          dtype=jnp.float32)
    prefix = jax.random.randint(jax.random.PRNGKey(46), (1, 6), 0, V)
    params = model.init(jax.random.PRNGKey(47), prefix)["params"]
    suffixes = jax.random.randint(jax.random.PRNGKey(48), (2, 4), 0, V)
    state = prefill_prefix(model, params, prefix,
                           max_total_len=6 + 4 + N, chunk_slack=4)
    fast = decode_with_prefix(model, params, state, suffixes, N,
                              fast_prefill=True)
    slow = decode_with_prefix(model, params, state, suffixes, N,
                              fast_prefill=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_prefill_prefix_chunk_slack_rejected_on_dense_models(dense_lm):
    from container_engine_accelerators_tpu.models.decode import (
        prefill_prefix,
    )

    model, params, _ = dense_lm
    with pytest.raises(ValueError, match="chunk_slack"):
        prefill_prefix(model, params, jnp.zeros((1, 4), jnp.int32),
                       max_total_len=20, chunk_slack=4)


def test_prefill_prefix_negative_chunk_slack_rejected():
    from container_engine_accelerators_tpu.models.decode import (
        prefill_prefix,
    )

    model = TransformerLM(vocab_size=V, embed_dim=E, num_layers=1,
                          num_heads=H, max_seq_len=MAXLEN,
                          attention_window=8, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    with pytest.raises(ValueError, match="chunk_slack must be"):
        prefill_prefix(model, params, jnp.zeros((1, 4), jnp.int32),
                       max_total_len=20, chunk_slack=-2)


def test_beam_windowed_equals_exhaustive_truncated_scoring():
    """Beam search on a sliding-window model: the ring cache (which
    the beam gather/fan-out reorders every step) must score paths
    exactly as the dense windowed forward does — pinned against the
    exhaustive argmax with first-EOS truncated scoring, with the
    window short enough that the ring wraps inside the scored
    region."""
    import itertools

    v, n, eos, w = 5, 3, 2, 3
    model = TransformerLM(vocab_size=v, embed_dim=16, num_layers=1,
                          num_heads=2, max_seq_len=8,
                          attention_window=w, dtype=jnp.float32)
    prompt = jnp.asarray([[1, 3]], jnp.int32)
    params = model.init(jax.random.PRNGKey(8), prompt)["params"]
    seqs, scores = beam_search(model, params, prompt, n,
                               num_beams=v ** n, eos_id=eos)

    def truncated_score(path):
        seq = jnp.asarray([[1, 3, *path]], jnp.int32)
        logits = model.apply({"params": params}, seq, train=False)
        lp = jax.nn.log_softmax(
            np.asarray(logits)[0].astype(np.float32), axis=-1)
        score = 0.0
        for t in range(1, n + 1):
            score += lp[t, seq[0, t + 1]]
            if int(seq[0, t + 1]) == eos:
                break
        return score

    best_score, best_path = -np.inf, None
    seen = set()
    for path in itertools.product(range(v), repeat=n):
        canon = []
        done = False
        for tok in path:
            canon.append(eos if done else tok)
            done = done or tok == eos
        canon = tuple(canon)
        if canon in seen:
            continue
        seen.add(canon)
        score = truncated_score(canon)
        if score > best_score:
            best_score, best_path = score, canon
    np.testing.assert_array_equal(np.asarray(seqs[0, 0, 2:]),
                                  np.asarray(best_path))
    np.testing.assert_allclose(float(scores[0, 0]), best_score,
                               rtol=1e-4, atol=1e-4)
