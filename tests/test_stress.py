# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Concurrency stress: the Python analog of `go test -race`.

The reference runs its whole suite under the race detector
(Makefile:19-21); Python has no TSan, so this hammers the same shared
state from many threads at once and uses the gRPC status taxonomy as
the detector: an unguarded-race exception inside a servicer surfaces
to the client as StatusCode.UNKNOWN, while every *legitimate* outcome
maps to a known code (INVALID_ARGUMENT for unhealthy/unknown devices
mid-flap, UNAVAILABLE/CANCELLED while the serve loop swaps sockets on
hot-plug). Threads: Allocate hammerers, a ListAndWatch consumer that
re-dials across re-serves, a health flapper, and a chip hot-plugger.
"""

import os
import random
import threading
import time

import grpc
import pytest

from container_engine_accelerators_tpu.chip import PyChipBackend
from container_engine_accelerators_tpu.plugin import api
from container_engine_accelerators_tpu.plugin import manager as manager_mod
from container_engine_accelerators_tpu.plugin.manager import TpuManager
from tests.plugin_helpers import ServingManager, short_tmpdir

STRESS_SECONDS = float(os.environ.get("STRESS_SECONDS", "4"))

# Statuses that are legitimate while health flaps and sockets churn.
_TOLERATED = {
    grpc.StatusCode.INVALID_ARGUMENT,   # unhealthy / just-removed device
    grpc.StatusCode.UNAVAILABLE,        # socket swapped by re-serve
    grpc.StatusCode.CANCELLED,          # stream torn down at stop
    grpc.StatusCode.DEADLINE_EXCEEDED,  # re-serve pause outlived an RPC
}


@pytest.fixture
def fast_intervals(monkeypatch):
    monkeypatch.setattr(manager_mod, "SOCKET_CHECK_INTERVAL_S", 0.05)
    monkeypatch.setattr(manager_mod, "CHIP_CHECK_INTERVAL_S", 0.2)


def _current_socket(plugin_dir):
    socks = [f for f in os.listdir(plugin_dir)
             if f.startswith("tpu-") and f.endswith(".sock")]
    if not socks:
        return None
    return os.path.join(plugin_dir, sorted(socks)[-1])


class _Failures:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def add(self, what):
        with self._lock:
            self.items.append(what)


def _allocate_hammer(plugin_dir, stop, failures, stats, seed, id_pool):
    rng = random.Random(seed)
    while not stop.is_set():
        sock = _current_socket(plugin_dir)
        if sock is None:
            time.sleep(0.01)
            continue
        try:
            with grpc.insecure_channel(f"unix://{sock}") as ch:
                stub = api.DevicePluginV1Beta1Stub(ch)
                for _ in range(20):
                    if stop.is_set():
                        break
                    ids = rng.sample(id_pool,
                                     rng.randint(1, min(3,
                                                        len(id_pool))))
                    try:
                        resp = stub.Allocate(
                            api.v1beta1_pb2.AllocateRequest(
                                container_requests=[
                                    api.v1beta1_pb2.
                                    ContainerAllocateRequest(
                                        devicesIDs=ids)]),
                            timeout=2)
                        stats["allocates"] += 1
                        cresp = resp.container_responses[0]
                        # Internal-consistency invariant: the env
                        # contract must cover exactly the handed nodes.
                        vis = cresp.envs["TPU_VISIBLE_DEVICES"]
                        got = {os.path.basename(d.host_path)
                               for d in cresp.devices}
                        want = {f"accel{c}" for c in vis.split(",")}
                        if got != want:
                            failures.add(
                                f"devices {got} != envs {want}")
                    except grpc.RpcError as e:
                        if e.code() not in _TOLERATED:
                            failures.add(
                                f"Allocate {ids}: {e.code()} "
                                f"{e.details()}")
        except grpc.RpcError:
            time.sleep(0.01)


def _watch_loop(plugin_dir, stop, failures, stats):
    while not stop.is_set():
        sock = _current_socket(plugin_dir)
        if sock is None:
            time.sleep(0.01)
            continue
        try:
            with grpc.insecure_channel(f"unix://{sock}") as ch:
                stub = api.DevicePluginV1Beta1Stub(ch)
                stream = stub.ListAndWatch(api.v1beta1_pb2.Empty(),
                                           timeout=STRESS_SECONDS + 10)
                for resp in stream:
                    stats["watch_updates"] += 1
                    seen = [d.ID for d in resp.devices]
                    if len(seen) != len(set(seen)):
                        failures.add(f"duplicate device ids: {seen}")
                    if stop.is_set():
                        break
        except grpc.RpcError as e:
            if e.code() not in _TOLERATED:
                failures.add(f"ListAndWatch: {e.code()} {e.details()}")
            time.sleep(0.01)


def _health_flapper(manager, stop, stats, flap_devices):
    flip = False
    while not stop.is_set():
        flip = not flip
        health = api.UNHEALTHY if flip else api.HEALTHY
        for dev in flap_devices:
            manager.set_device_health(dev, health)
            stats["flaps"] += 1
        time.sleep(0.005)


def _hot_plugger(node, stop, stats):
    while not stop.is_set():
        for i in (4, 5):
            node.add_chip(i)
        stats["plugs"] += 1
        time.sleep(0.3)
        if stop.is_set():
            break
        for i in (4, 5):
            try:
                node.remove_chip(i)
            except FileNotFoundError:
                pass
        stats["plugs"] += 1
        time.sleep(0.3)


@pytest.mark.slow
@pytest.mark.parametrize("partition", ["", "2x2"])
def test_allocate_listandwatch_under_churn(fake_node, fast_intervals,
                                           partition):
    """Whole-chip mode and subslice mode (SliceManager re-solves the
    tiling on hot-plug churn — including non-uniform transients the
    manager must survive — and health flaps route through it)."""
    from container_engine_accelerators_tpu.plugin.config import (
        TpuConfig,
    )

    for i in range(4):
        fake_node.add_chip(i)
    fake_node.set_topology("2x2")
    manager = TpuManager(dev_dir=fake_node.dev_dir,
                         state_dir=fake_node.state_dir,
                         backend=PyChipBackend(),
                         tpu_config=TpuConfig(
                             tpu_partition_size=partition))
    manager.start()
    if partition:
        # 2x2 tiling of the 2x2 node -> one subslice device.
        id_pool = ["tpu-2x2-0", "tpu-2x2-1", "accel0"]
        flap_devices = ("tpu-2x2-0",)
        settle_device = "tpu-2x2-0"
    else:
        id_pool = [f"accel{i}" for i in range(6)]
        flap_devices = ("accel1", "accel2")
        settle_device = "accel1"

    plugin_dir = short_tmpdir()
    stop = threading.Event()
    failures = _Failures()
    stats = {"allocates": 0, "watch_updates": 0, "flaps": 0, "plugs": 0}

    with ServingManager(manager, plugin_dir):
        threads = [
            threading.Thread(target=_allocate_hammer,
                             args=(plugin_dir, stop, failures, stats,
                                   s, id_pool),
                             daemon=True)
            for s in (1, 2, 3)
        ] + [
            threading.Thread(target=_watch_loop,
                             args=(plugin_dir, stop, failures, stats),
                             daemon=True),
            threading.Thread(target=_health_flapper,
                             args=(manager, stop, stats, flap_devices),
                             daemon=True),
            threading.Thread(target=_hot_plugger,
                             args=(fake_node, stop, stats), daemon=True),
        ]
        for t in threads:
            t.start()
        # Run for STRESS_SECONDS, then keep going (bounded) until every
        # churn axis has demonstrably fired — a fixed window under a
        # loaded CI machine can starve a thread of its first iteration,
        # which would fail the coverage asserts below without any bug.
        deadline = time.monotonic() + max(STRESS_SECONDS * 10, 30)
        time.sleep(STRESS_SECONDS)
        while (time.monotonic() < deadline
               and not all(stats[k] > 0 for k in stats)):
            time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), f"thread {t} wedged"

        # The node must end functional: settle health and allocate.
        for dev in flap_devices:
            manager.set_device_health(dev, api.HEALTHY)
        specs = manager.device_specs(settle_device)
        assert len(specs) == (4 if partition else 1)

    assert not failures.items, (failures.items[:10], stats)
    # The churn must actually have exercised every axis.
    assert all(stats[k] > 0 for k in stats), stats


@pytest.mark.slow
def test_dead_streams_release_server_threads_immediately(fake_node,
                                                         fast_intervals):
    """Flapping-kubelet resource exhaustion (VERDICT r2 weak #7).

    Fill the server's whole thread pool with ListAndWatch streams,
    cancel them all client-side, and require a fresh Allocate to get a
    thread well inside the 5s stream poll quantum — the cancellation
    callback (manager.wake_streams) must free parked stream threads at
    disconnect time, not at the next wait_for_change() timeout.
    """
    from container_engine_accelerators_tpu.plugin.config import TpuConfig

    for i in range(2):
        fake_node.add_chip(i)
    fake_node.set_topology("2x1")
    manager = TpuManager(dev_dir=fake_node.dev_dir,
                         state_dir=fake_node.state_dir,
                         backend=PyChipBackend(),
                         tpu_config=TpuConfig())
    manager.start()
    plugin_dir = short_tmpdir()
    with ServingManager(manager, plugin_dir):
        sock = _current_socket(plugin_dir)
        channels, streams = [], []
        try:
            # 8 = the serve loop's ThreadPoolExecutor(max_workers=8):
            # each open stream parks one worker in wait_for_change().
            for _ in range(8):
                ch = grpc.insecure_channel(f"unix://{sock}")
                stream = api.DevicePluginV1Beta1Stub(ch).ListAndWatch(
                    api.v1beta1_pb2.Empty())
                next(iter(stream))  # first payload => servicer running
                channels.append(ch)
                streams.append(stream)
            # Let every worker park inside wait_for_change() so the
            # cancellations hit mid-quantum (without the callback this
            # reproducibly costs ~4s of dead thread time).
            time.sleep(1.0)
            for stream in streams:
                stream.cancel()
            t0 = time.monotonic()
            with grpc.insecure_channel(f"unix://{sock}") as ch:
                stub = api.DevicePluginV1Beta1Stub(ch)
                resp = stub.Allocate(
                    api.v1beta1_pb2.AllocateRequest(container_requests=[
                        api.v1beta1_pb2.ContainerAllocateRequest(
                            devicesIDs=["accel0"])]),
                    timeout=3)
            elapsed = time.monotonic() - t0
            assert resp.container_responses[0].devices
            # Well under the 5s poll quantum that bounded thread reuse
            # before the cancellation callback existed.
            assert elapsed < 3.0, f"Allocate waited {elapsed:.1f}s for " \
                                  f"a server thread"
        finally:
            for ch in channels:
                ch.close()
