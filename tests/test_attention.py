# Copyright 2026 The container-engine-accelerators-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Flash-attention kernel and TransformerLM tests (CPU interpret mode).

Every flash test is an equality check against dense attention — the
kernel is exact, so tolerances only cover f32 reduction order.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from container_engine_accelerators_tpu.models import TransformerLM
from container_engine_accelerators_tpu.models.transformer import (
    make_apply_fn,
    next_token_loss_fn,
)
from container_engine_accelerators_tpu.ops import (
    flash_attention,
    softmax_cross_entropy,
)
from container_engine_accelerators_tpu.parallel import (
    build_context_mesh,
    dot_product_attention,
    ring_attention,
)

# Tier-1 budget: this module compiles many distinct XLA programs and
# runs minutes on the CI CPU mesh. It only became collectable when the
# shard_map compat shim fixed the jax-version import error, and
# including it would blow the 870s tier-1 cap — so it runs in the full
# lane (`make test` / pytest without `-m "not slow"`) instead.
pytestmark = pytest.mark.slow


B, S, H, D = 2, 200, 4, 32  # S deliberately not a multiple of 128


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), jnp.float32)
                 for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(qkv, causal):
    q, k, v = qkv
    want = dot_product_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_block_size_invariance(qkv):
    """The tunable seq tile must not change results (fwd + bwd)."""
    q, k, v = qkv
    want = flash_attention(q, k, v, causal=True, block=128)
    got = flash_attention(q, k, v, causal=True, block=256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss(block):
        return jax.grad(lambda x: jnp.sum(flash_attention(
            x, k, v, causal=True, block=block) ** 2))(q)

    np.testing.assert_allclose(np.asarray(loss(256)),
                               np.asarray(loss(128)),
                               rtol=2e-4, atol=2e-4)
    with pytest.raises(ValueError, match="multiple of 128"):
        flash_attention(q, k, v, block=100)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(qkv, causal):
    q, k, v = qkv

    def f_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def d_loss(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    want = jax.grad(d_loss, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(f_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bf16_io():
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (1, 128, 2, 64), jnp.bfloat16)
               for kk in ks)
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    want = dot_product_attention(q.astype(jnp.float32),
                                 k.astype(jnp.float32),
                                 v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(want), rtol=2e-2, atol=2e-2)


def test_flash_rejects_shape_mismatch(qkv):
    q, k, _ = qkv
    with pytest.raises(ValueError, match="shapes differ"):
        flash_attention(q, k, jnp.zeros((B, S, H, D + 1)))


def _tiny_lm(attention_fn=None):
    return TransformerLM(vocab_size=97, embed_dim=32, num_layers=2,
                         num_heads=2, max_seq_len=64,
                         dtype=jnp.float32, attention_fn=attention_fn)


def test_transformer_forward_shape():
    model = _tiny_lm()
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens, train=False)
    logits = model.apply(variables, tokens, train=False)
    assert logits.shape == (2, 16, 97)
    assert logits.dtype == jnp.float32


def test_transformer_attention_fn_pluggable():
    """Same weights, three attention schedules, identical logits —
    the property that makes checkpoints portable across single-chip
    flash and mesh-parallel ring deployments."""
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 97)
    dense_lm = _tiny_lm(dot_product_attention)
    variables = dense_lm.init(jax.random.PRNGKey(0), tokens, train=False)
    want = dense_lm.apply(variables, tokens, train=False)

    mesh = build_context_mesh(context=4)
    for fn in (flash_attention,
               functools.partial(ring_attention, mesh)):
        got = _tiny_lm(fn).apply(variables, tokens, train=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_transformer_next_token_training_step():
    model = _tiny_lm()
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 24), 0, 97)
    variables = model.init(jax.random.PRNGKey(0), tokens, train=False)
    apply_fn = make_apply_fn(model)
    loss_fn = next_token_loss_fn(
        lambda lg, lb: jnp.mean(softmax_cross_entropy(lg, lb)))

    def objective(params):
        logits, _ = apply_fn({"params": params}, tokens, True)
        return loss_fn(logits, tokens)

    params = variables["params"]
    loss0, grads = jax.value_and_grad(objective)(params)
    assert jnp.isfinite(loss0)
    # One SGD step must reduce the loss on the same batch.
    params = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, params,
                                    grads)
    loss1 = objective(params)
    assert loss1 < loss0


@pytest.mark.parametrize("causal", [False, True])
def test_streaming_matches_resident(qkv, causal):
    """Grid-streamed kernels (seq > VMEM budget) == resident kernels,
    forward and backward, including the ragged final tile.

    block=128 so S=200 pads to 2 tiles: the cross-grid-step machinery
    (scratch persistence, the exp(m - new_m) correction against a
    real prior max, the causal/padding run-skip) actually executes —
    at the default block the grid would be 1x1 and none of it would.
    """
    q, k, v = qkv
    want = flash_attention(q, k, v, causal=causal, block=128,
                           streaming=False)
    got = flash_attention(q, k, v, causal=causal, block=128,
                          streaming=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss(streaming):
        return jax.grad(
            lambda t: jnp.sum(flash_attention(
                t[0], t[1], t[2], causal=causal, block=128,
                streaming=streaming) ** 2))((q, k, v))

    for g, w in zip(loss(True), loss(False)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_streaming_multitile_matches_dense(causal):
    """4+ streamed tiles against the dense reference, fwd + grad."""
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(kk, (1, 512, 2, 32), jnp.float32)
               for kk in ks)

    want = dot_product_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block=128,
                          streaming=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def f_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block=128, streaming=True) ** 2)

    def d_loss(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v,
                                             causal=causal) ** 2)

    want_g = jax.grad(d_loss, argnums=(0, 1, 2))(q, k, v)
    got_g = jax.grad(f_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got_g, want_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_streaming_auto_threshold():
    """Auto mode streams only above the resident VMEM budget."""
    from container_engine_accelerators_tpu.ops import attention as A

    assert not A._use_streaming(8192, 128, 2, None)
    assert A._use_streaming(16384, 128, 2, None)
    assert A._use_streaming(256, 128, 2, True)  # explicit override
    assert not A._use_streaming(10 ** 9, 128, 2, False)


def test_flash_property_sweep():
    """Randomized shapes x modes x causality vs dense attention.

    One seed per case, shapes chosen to cross tile boundaries
    (ragged final tiles, S < block, S == block, multi-tile) — the
    places where padding/masking bugs live.
    """
    rng = np.random.RandomState(0)
    cases = [
        # (B, S, H, D, block)
        (1, 64, 1, 8, 128),     # S < block -> single padded tile
        (2, 128, 2, 16, 128),   # S == block exactly
        (1, 129, 1, 8, 128),    # one ragged row over the boundary
        (3, 384, 2, 8, 128),    # 3 exact tiles
        (1, 300, 4, 32, 256),   # ragged with a larger block
    ]
    for (b, s, h, d, block) in cases:
        for causal in (False, True):
            for streaming in (False, True):
                q, k, v = (
                    jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
                    for _ in range(3))
                want = dot_product_attention(q, k, v, causal=causal)
                got = flash_attention(q, k, v, causal=causal,
                                      block=block,
                                      streaming=streaming)
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want),
                    rtol=3e-5, atol=3e-5,
                    err_msg=f"case {(b, s, h, d, block)} "
                            f"causal={causal} streaming={streaming}")


@pytest.mark.parametrize("streaming", [False, True])
def test_sliding_window_matches_masked_dense(streaming):
    """window=W == dense attention with the (p - W, p] band mask,
    fwd and bwd, across tile boundaries (W not a block multiple)."""
    ks = jax.random.split(jax.random.PRNGKey(13), 3)
    q, k, v = (jax.random.normal(kk, (1, 300, 2, 16), jnp.float32)
               for kk in ks)
    W = 70

    def dense_window(q, k, v):
        s = 300
        scale = 1.0 / np.sqrt(16)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        qp = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
        kp = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        mask = (qp >= kp) & (kp > qp - W)
        scores = jnp.where(mask, scores, -1e9)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    want = dense_window(q, k, v)
    got = flash_attention(q, k, v, causal=True, block=128,
                          streaming=streaming, window=W)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    want_g = jax.grad(lambda t: jnp.sum(
        dense_window(t[0], t[1], t[2]) ** 2))((q, k, v))
    got_g = jax.grad(lambda t: jnp.sum(flash_attention(
        t[0], t[1], t[2], causal=True, block=128,
        streaming=streaming, window=W) ** 2))((q, k, v))
    for g, w in zip(got_g, want_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_sliding_window_validation(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="requires causal"):
        flash_attention(q, k, v, causal=False, window=8)
    with pytest.raises(ValueError, match=">= 0"):
        flash_attention(q, k, v, causal=True, window=-1)
    # window >= seq is plain causal attention.
    want = flash_attention(q, k, v, causal=True, block=128)
    got = flash_attention(q, k, v, causal=True, block=128,
                          window=10 ** 6)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_chunked_oracle_matches_dense(causal):
    """The chunked f32 oracle (the 8k-32k on-chip numerics reference,
    VERDICT r2 weak #4) must agree with dense attention bit-tightly
    at lengths where both compile."""
    from container_engine_accelerators_tpu.parallel import (
        chunked_reference_attention,
        dot_product_attention,
    )

    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = (jax.random.normal(key, (2, 512, 4, 64), jnp.bfloat16)
               for key in ks)
    dense = dot_product_attention(q.astype(jnp.float32),
                                  k.astype(jnp.float32),
                                  v.astype(jnp.float32), causal=causal)
    oracle = chunked_reference_attention(q, k, v, causal=causal,
                                         chunk=128)
    np.testing.assert_allclose(np.asarray(oracle), np.asarray(dense),
                               rtol=2e-6, atol=2e-6)
    with pytest.raises(ValueError, match="not divisible"):
        chunked_reference_attention(q, k, v, chunk=100)


def test_chunked_oracle_bounds_flash():
    """The flash kernel's error vs the oracle matches its error vs
    dense — the bound recorded on-chip for long sequences is the same
    quantity measured here against both references."""
    from container_engine_accelerators_tpu.ops.attention import (
        flash_attention,
    )
    from container_engine_accelerators_tpu.parallel import (
        chunked_reference_attention,
    )

    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(key, (1, 256, 2, 64), jnp.float32)
               for key in ks)
    oracle = chunked_reference_attention(q, k, v, causal=True,
                                         chunk=128)
    got = flash_attention(q, k, v, causal=True, block=128)
    err = float(jnp.max(jnp.abs(jnp.asarray(got, jnp.float32)
                                - oracle)))
    assert err < 2e-5, err
