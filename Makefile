# Build/test/package entry points.
# Parity with the reference's Makefile targets (test, presubmit,
# container, push) plus the native library builds.

REGISTRY ?= gcr.io/gke-release
PLUGIN_IMAGE ?= $(REGISTRY)/tpu-device-plugin
INSTALLER_IMAGE ?= $(REGISTRY)/libtpu-installer
PARTITIONER_IMAGE ?= $(REGISTRY)/tpu-partitioner
VERSION ?= v0.1.0

all: native

native:
	$(MAKE) -C native/tpuinfo
	$(MAKE) -C native/sampler
	$(MAKE) -C demo/tpu-error

test: native
	$(MAKE) -C native/tpuinfo test
	$(MAKE) test-native-asan
	python3 -m pytest tests/ -q

test-native:
	$(MAKE) -C native/tpuinfo test

# ASan+UBSan pass over the native layer: tpuinfo unit tests plus the
# sampler feed-parser fuzz harness (the C++ analog of the reference's
# `go test -race` on every run).
test-native-asan:
	$(MAKE) -C native/tpuinfo test-asan
	$(MAKE) -C native/sampler test-asan

presubmit:
	./build/check_python.sh
	./build/check_logging.sh
	./build/check_boilerplate.sh
	python3 -m container_engine_accelerators_tpu.analysis
	JAX_PLATFORMS=cpu python3 tools/program_manifest.py --check
	python3 tools/perf_ledger.py check
	JAX_PLATFORMS=cpu python3 tools/slo_check.py --fast
	JAX_PLATFORMS=cpu python3 tools/serving_chaos_check.py --fast
	JAX_PLATFORMS=cpu python3 tools/fleet_check.py --fast
	JAX_PLATFORMS=cpu python3 tools/router_check.py --fast
	JAX_PLATFORMS=cpu python3 tools/bench_serving_occupancy.py \
		--spec-check

# Project-native analysis gate: the AST lint must report ZERO
# findings over the tree while every seeded fixture violation fires;
# the lock-order sanitizer (CEA_TPU_TSAN=1) must flag the
# inverted-lock fixture and run clean over the engine/elastic/
# placement suites; the retrace guard must hold the engine's
# program-count bound (buckets + insert + step) over a mixed-traffic
# trace and catch the seeded retracer. Pure CPU, ~3 min.
analysis-check:
	JAX_PLATFORMS=cpu python3 tools/analysis_check.py

# Program-manifest gate: lower every registered hot program (paged +
# dense engine trios, parallel train step) with canonical example
# args, run the IR hygiene rules (donation-miss, const-capture,
# host-callback-in-hot-path, weak-type-leak, dtype-upcast — zero
# findings required), and diff the derived fingerprints against the
# committed PROGRAM_MANIFEST.json: unexpected programs, donation/
# aval drift, or >10% FLOPs/bytes movement fail with --update
# instructions. Pure CPU, ~1 min.
program-check:
	JAX_PLATFORMS=cpu python3 tools/program_manifest.py --check

# Tracer leak/regression guard: fake-chip plugin up, one Allocate
# through the real gRPC surface, fail on empty /debug/trace or any
# span left open. Pure CPU, ~2s.
trace-check:
	python3 tools/trace_check.py

# Flight-recorder guard: fake-chip plugin + a second process's
# journal, swept by tools/tpu_diagnose.py; fails unless the bundle
# has a non-empty MERGED trace (both processes), a varz snapshot
# with the RPC histogram, and the node's device state. Pure CPU.
diagnose-check:
	python3 tools/diagnose_check.py

# Efficiency-accounting guard: a synthetic journal with known
# compile/data-wait/step timings must replay to the exact goodput
# ratio (buckets summing to wall within 1%), and a real tiny Trainer
# on the CPU fake backend must produce the analytic 6NBS FLOPs
# fallback exactly + publish the MFU gauge. Pure CPU, seconds.
goodput-check:
	JAX_PLATFORMS=cpu python3 tools/goodput_check.py

# Elastic-training guard: a 4-host fake fleet has one host SIGKILLed
# (chips wedged -> plugin health flip) and one SIGSTOPped (stale
# heartbeat) mid-step; the ElasticSupervisor must evict both (exactly
# one train.eviction + train.reshape event each), reshape the mesh
# 4x2 -> 3x2 -> 2x2, resume resharded from the latest async
# checkpoint, and converge to the uninterrupted run's loss with
# goodput >= 0.5 and async checkpoint badput < 10% of sync.
# CPU fake backend, ~3 min.
chaos-check:
	JAX_PLATFORMS=cpu python3 tools/chaos_check.py

# Placement-subsystem guard: on the fake-chip backend, a mixed
# allocate trace must show the PlacementScorer retaining at least as
# much (and in total strictly more) largest-allocatable-box capacity
# than first-fit, and a forced-fragmentation episode must yield
# exactly one repartition proposal that is applied only once the node
# is drained and restores full-box allocations. Pure CPU, seconds.
placement-check:
	python3 tools/placement_check.py

# Continuous-batching regression guard: replay one Poisson arrival
# trace through the slot engine (real decode, CPU fake backend) and
# the pre-engine sequential-batch policy; fail unless engine goodput
# is >= 2x the baseline on the same trace AND every greedy output is
# bit-identical to per-request decode(). Pure CPU, ~1 min.
occupancy-check:
	JAX_PLATFORMS=cpu python3 tools/bench_serving_occupancy.py --check

# Paged-KV capacity guard: replay one shared-prefix Poisson trace
# (80% of requests opening with one system prompt) through the paged
# block-pool engine and the dense per-slot pool at EQUAL KV HBM
# budget; fail unless the paged pool sustains >= 2x concurrent
# rows/step, its prefix index actually hit (prefix_hit_rate > 0),
# and every greedy stream (both pools) is bit-identical to
# per-request decode(). Pure CPU, ~2 min.
paging-check:
	JAX_PLATFORMS=cpu python3 tools/bench_serving_occupancy.py \
		--paging-check

# Tiered-KV guard: replay one long-tail prefix trace (more distinct
# system prompts than the arena holds) through the paged engine three
# ways — bf16 + host spill tier, bf16 without it, and an int8 arena
# at EQUAL HBM bytes; fail unless spill beats re-prefill on
# token-forward goodput, the int8 arena sustains >= 1.8x the bf16
# rows/step, and every greedy stream is bit-identical to its matching
# dense-fallback decode(). Pure CPU, ~3 min.
spill-check:
	JAX_PLATFORMS=cpu python3 tools/bench_serving_occupancy.py \
		--spill-check

# Speculative-decode guard: replay the occupancy Poisson trace
# through the engine with a self-draft configured (--spec-k chunks)
# and again with speculation off; fail unless the speculative replay
# retains >= 2x the batcher baseline's goodput with the draft's
# device calls on the ledger, self-draft acceptance holds its floor,
# every greedy stream is bit-identical to per-request decode(), and
# both arenas (target + draft) release clean. Pure CPU, ~1 min.
spec-check:
	JAX_PLATFORMS=cpu python3 tools/bench_serving_occupancy.py \
		--spec-check

# Latency-attribution guard: replay a synthetic greedy trace with
# INJECTED KV-block starvation through the instrumented serving loop
# (_EngineService + paged engine, arena sized for ~2 of 4 slots);
# fail unless every retired request's attribution buckets sum to its
# wall time within 1%, the TTFT tail's top-ranked bucket is
# block_wait (the injected cause comes back NAMED), the
# tpu_serving_saturation plane read block-starved while the queue was
# backed up, and every greedy stream is token-identical to
# per-request decode() — the instrumentation must be
# stream-invisible. Pure CPU, ~1 min.
slo-check:
	JAX_PLATFORMS=cpu python3 tools/slo_check.py

# Serving-survivability guard: inject device-side faults into the
# engine's step/prefill/rehydrate sites (CEA_TPU_FAULT_PLAN) through
# the real _EngineService; the quarantine-and-rebuild supervisor must
# resume every greedy stream token-identical to uninterrupted
# decode(), leak zero slots/blocks, attribute the stall to the
# reqledger `recovery` bucket (sum-to-wall intact), emit exactly one
# quarantine/recovered event pair per episode, and finish a
# drain-under-fire inside the grace window with new admissions shed —
# all tsan-clean. Pure CPU, ~2 min.
serving-chaos-check:
	JAX_PLATFORMS=cpu python3 tools/serving_chaos_check.py

# Fleet observability gate: three real fake-chip engines + the
# jax-free observer; merged fleet p99s must EQUAL a pooled
# recomputation bucket-for-bucket, a SIGKILL'd engine must produce
# exactly one fleet.engine_down and leave the steer set in one poll,
# a draining engine is steered around WITHOUT a down event, a fresh
# SLO burst fires the fast burn window while the slow window holds,
# and the scale signal rises under load then decays. Pure CPU.
fleet-check:
	JAX_PLATFORMS=cpu python3 tools/fleet_check.py

# Engine-fleet router gate: real engine servers (one model seed)
# behind the jax-free serving.router front door; goodput must scale
# >= 3.2x from 1 to 4 engines on a mixed Poisson trace (row-work
# makespan), prefix-affinity routing must hold the fleet
# prefix_hit_rate at the single-engine baseline while a round-robin
# control degrades, a mid-stream SIGKILL must splice every greedy
# stream token-identically onto siblings, survivors must quiesce
# leak-free, and an empty steer set must shed 503 with a derived
# Retry-After. The journey leg rides the same chaos run: every
# chaos request must carry exactly ONE trace id end-to-end (router
# span, engine spans, both journey ledgers joined by request id —
# splice included), its router buckets must sum to wall within 1%,
# slo_report must name a nonzero bucket-named router tax, and the
# mean splice-free tax lands in the perf ledger as
# router_overhead_ms. Pure CPU.
router-check:
	JAX_PLATFORMS=cpu python3 tools/router_check.py

# Perf-ledger regression gate: validate every committed
# PERF_LEDGER.json row (schema exact, field-level messages) and
# compare each source's newest row against its newest SAME-RIG
# baseline — direction-aware (throughput down OR latency up) with a
# 10% tolerance, mirroring how program-check gates cost drift. A
# source with only foreign-rig baselines is a DOCUMENTED skip, never
# a silent pass; skipped_unmeasurable rows read as "no data".
# Intentional level changes: `python3 tools/perf_ledger.py accept
# --source <s> --note "<why>"`. Pure ledger read, no jax, ~1s.
perf-check:
	python3 tools/perf_ledger.py check

bench:
	python3 bench.py

container:
	docker build -t $(PLUGIN_IMAGE):$(VERSION) .
	docker build -t $(INSTALLER_IMAGE):$(VERSION) \
		-f deploy/libtpu-installer/ubuntu/Dockerfile \
		deploy/libtpu-installer

partition-tpu:
	docker build -t $(PARTITIONER_IMAGE):$(VERSION) \
		-f deploy/partition-tpu/Dockerfile .

push: container partition-tpu
	docker push $(PLUGIN_IMAGE):$(VERSION)
	docker push $(INSTALLER_IMAGE):$(VERSION)
	docker push $(PARTITIONER_IMAGE):$(VERSION)

clean:
	$(MAKE) -C native/tpuinfo clean
	$(MAKE) -C native/sampler clean
	$(MAKE) -C demo/tpu-error clean

.PHONY: all native test test-native test-native-asan presubmit bench \
	analysis-check program-check trace-check diagnose-check \
	goodput-check chaos-check placement-check occupancy-check \
	paging-check spill-check spec-check perf-check slo-check \
	serving-chaos-check fleet-check router-check container \
	partition-tpu push clean
